"""Atomic store transactions — the ``ceph::os::Transaction`` analog.

Mirrors src/os/Transaction.h: an ordered op list applied atomically by
a store. The op vocabulary is the subset the EC pipeline emits from
``generate_transactions`` (osd/ECTransaction.cc:916): touch, write,
zero, truncate, remove, setattr, rmattr. Each op is a plain record;
the store interprets them (src/os/memstore/MemStore.cc
``_do_transaction`` pattern).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class OpKind(enum.Enum):
    TOUCH = "touch"
    WRITE = "write"
    ZERO = "zero"
    TRUNCATE = "truncate"
    REMOVE = "remove"
    SETATTR = "setattr"
    RMATTR = "rmattr"
    #: rmattr that no-ops when the attr is absent — the xattr-
    #: tombstone replay path, where the target may never have had it
    RMATTR_TOLERANT = "rmattr_tolerant"


@dataclass
class Op:
    kind: OpKind
    oid: str
    offset: int = 0
    length: int = 0
    data: bytes = b""
    name: str = ""
    #: optional kernel-produced ZERO-INIT per-block crc32c values for
    #: WRITE ops (the fused encode+csum output riding the sub-write);
    #: stores that keep blob csums may adopt them instead of
    #: re-hashing, others ignore them. Advisory: they must describe
    #: ``data`` exactly (csum_block-aligned offset and length).
    csums: "tuple[int, ...] | None" = None
    csum_block: int = 0


@dataclass
class Transaction:
    """Ordered op list; built fluently, applied atomically."""

    ops: list[Op] = field(default_factory=list)

    def touch(self, oid: str) -> "Transaction":
        self.ops.append(Op(OpKind.TOUCH, oid))
        return self

    def write(
        self, oid: str, offset: int, data: bytes,
        csums=None, csum_block: int = 0,
    ) -> "Transaction":
        """``csums``/``csum_block``: optional zero-init per-block
        crc32c of ``data`` from the fused encode+csum kernel — see
        ``Op.csums``."""
        self.ops.append(
            Op(OpKind.WRITE, oid, offset=offset, length=len(data),
               data=bytes(data),
               csums=tuple(int(v) for v in csums) if csums is not None
               else None,
               csum_block=int(csum_block) if csums is not None else 0)
        )
        return self

    def zero(self, oid: str, offset: int, length: int) -> "Transaction":
        self.ops.append(Op(OpKind.ZERO, oid, offset=offset, length=length))
        return self

    def truncate(self, oid: str, size: int) -> "Transaction":
        self.ops.append(Op(OpKind.TRUNCATE, oid, offset=size))
        return self

    def remove(self, oid: str) -> "Transaction":
        self.ops.append(Op(OpKind.REMOVE, oid))
        return self

    def setattr(self, oid: str, name: str, value: bytes) -> "Transaction":
        self.ops.append(Op(OpKind.SETATTR, oid, name=name, data=bytes(value)))
        return self

    def rmattr(
        self, oid: str, name: str, ignore_missing: bool = False
    ) -> "Transaction":
        """Remove an attr; strict by default (KeyError when absent).
        ``ignore_missing`` emits RMATTR_TOLERANT: a no-op on absence."""
        kind = OpKind.RMATTR_TOLERANT if ignore_missing else OpKind.RMATTR
        self.ops.append(Op(kind, oid, name=name))
        return self

    def append(self, other: "Transaction") -> "Transaction":
        """Concatenate another transaction's ops (Transaction::append)."""
        self.ops.extend(other.ops)
        return self

    # -- wire serialization (Transaction::encode/decode analog) --------
    # Explicit stable codes, independent of OpKind declaration order:
    # these live in persisted FileStore journals and ECSubWrite
    # payloads, so renumbering silently corrupts replay. New kinds
    # append new codes; never reuse one.
    _KIND_CODE = {
        OpKind.TOUCH: 0,
        OpKind.WRITE: 1,
        OpKind.ZERO: 2,
        OpKind.TRUNCATE: 3,
        OpKind.REMOVE: 4,
        OpKind.SETATTR: 5,
        OpKind.RMATTR: 6,
        OpKind.RMATTR_TOLERANT: 7,
    }
    assert len(_KIND_CODE) == len(OpKind), "every OpKind needs a wire code"
    assert len(set(_KIND_CODE.values())) == len(_KIND_CODE), "codes must be unique"

    def to_bytes(self) -> bytes:
        """Compact binary encoding for ECSubWrite payloads: version
        byte, op count, then per op kind/oid/offset/length/name/data
        with u32 length prefixes (the versioned encode/decode pattern
        of src/os/Transaction.h). Transactions carrying kernel csums
        encode as v2 (each op appends csum_block + u32 csum list);
        csum-free transactions stay byte-identical v1, so the frozen
        golden payloads and mixed-version peers are both safe."""
        import struct

        ver = 2 if any(op.csums is not None for op in self.ops) else 1
        out = bytearray()
        out += struct.pack("<BI", ver, len(self.ops))
        for op in self.ops:
            oid = op.oid.encode()
            name = op.name.encode()
            out += struct.pack(
                "<BI", self._KIND_CODE[op.kind], len(oid)
            )
            out += oid
            out += struct.pack("<QQI", op.offset, op.length, len(name))
            out += name
            out += struct.pack("<I", len(op.data))
            out += op.data
            if ver >= 2:
                csums = op.csums or ()
                out += struct.pack("<II", op.csum_block, len(csums))
                for v in csums:
                    out += struct.pack("<I", v)
        return bytes(out)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Transaction":
        import struct

        pos = 0

        def take(n: int) -> bytes:
            nonlocal pos
            if pos + n > len(raw):
                raise ValueError(
                    f"truncated transaction encoding at byte {pos}+{n}"
                )
            out = raw[pos : pos + n]
            pos += n
            return out

        kinds = list(OpKind)
        ver, count = struct.unpack("<BI", take(5))
        if ver not in (1, 2):
            raise ValueError(f"unsupported transaction encoding v{ver}")
        txn = cls()
        for _ in range(count):
            code, oid_len = struct.unpack("<BI", take(5))
            if code >= len(kinds):
                raise ValueError(f"unknown op kind code {code}")
            oid = take(oid_len).decode()
            offset, length, name_len = struct.unpack("<QQI", take(20))
            name = take(name_len).decode()
            (data_len,) = struct.unpack("<I", take(4))
            data = bytes(take(data_len))
            csums, csum_block = None, 0
            if ver >= 2:
                csum_block, n_csums = struct.unpack("<II", take(8))
                if n_csums:
                    csums = struct.unpack(
                        f"<{n_csums}I", take(4 * n_csums)
                    )
                else:
                    csum_block = 0
            txn.ops.append(
                Op(kinds[code], oid, offset=offset, length=length,
                   data=data, name=name, csums=csums,
                   csum_block=csum_block)
            )
        if pos != len(raw):
            raise ValueError(
                f"{len(raw) - pos} trailing bytes after transaction ops"
            )
        return txn

    def oids(self) -> list[str]:
        """Distinct objects touched, in first-touch order."""
        seen: list[str] = []
        for op in self.ops:
            if op.oid not in seen:
                seen.append(op.oid)
        return seen

    def empty(self) -> bool:
        return not self.ops

    def __len__(self) -> int:
        return len(self.ops)
