"""Local object persistence — the ``ObjectStore`` boundary.

Behavioral mirror of the reference's store contract
(src/os/ObjectStore.h ``queue_transactions`` + src/os/Transaction.h):
writes arrive as ordered, atomic ``Transaction`` op lists; reads are
direct. ``MemStore`` (src/os/memstore/) is the in-RAM implementation
the reference uses to run its OSD pipeline tests hardware-free; ours
plays the same role for the TPU pipeline tests.
"""

from .transaction import Op, OpKind, Transaction
from .memstore import MemStore
from .filestore import FileStore
from .blockstore import BlockStore, CsumError

__all__ = [
    "BlockStore",
    "CsumError",
    "FileStore",
    "MemStore",
    "Op",
    "OpKind",
    "Transaction",
]
