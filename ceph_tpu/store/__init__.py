"""Local object persistence — the ``ObjectStore`` boundary.

Behavioral mirror of the reference's store contract
(src/os/ObjectStore.h ``queue_transactions`` + src/os/Transaction.h):
writes arrive as ordered, atomic ``Transaction`` op lists; reads are
direct. ``MemStore`` (src/os/memstore/) is the in-RAM implementation
the reference uses to run its OSD pipeline tests hardware-free; ours
plays the same role for the TPU pipeline tests.
"""

from .transaction import Op, OpKind, Transaction
from .memstore import MemStore
from .filestore import FileStore
from .blockstore import BlockStore, CsumError

__all__ = [
    "BlockStore",
    "CsumError",
    "FileStore",
    "MemStore",
    "Op",
    "OpKind",
    "Transaction",
]


def open_store(data_path: str):
    """Open an existing OSD store dir with the backend it was created
    with: the ``backend`` marker the CLI writes, else device-file
    detection. Shared by the dev-cluster CLI and the offline
    objectstore tool so backend detection cannot diverge."""
    import os

    marker = os.path.join(data_path, "backend")
    if os.path.exists(marker):
        kind = open(marker).read().strip()
    else:
        kind = (
            "block" if os.path.exists(os.path.join(data_path, "block"))
            else "file"
        )
    return BlockStore(data_path) if kind == "block" else FileStore(data_path)
