"""Block allocators — the BlueStore allocator family analog
(src/os/bluestore/{Bitmap,Btree,Hybrid}Allocator + FreelistManager).

BlueStore manages a raw block device: every blob write asks an
allocator for extents and every deletion releases them. The reference
ships six implementations with different fragmentation/memory
trade-offs; the two structural archetypes (plus the hybrid that
combines them) are here:

- ``BtreeAllocator`` — sorted free-extent map (offset-keyed),
  best-fit allocation, coalescing release. The Avl/Btree/Btree2
  shape.
- ``BitmapAllocator`` — one bit per alloc-unit, first-fit scan with a
  rolling cursor. Constant memory, worst-case linear scan; the shape
  the reference uses when btree metadata would blow up.
- ``HybridAllocator`` — btree until its extent count exceeds a cap,
  then spills the most fragmented runs to a bitmap child (the
  reference's Hybrid avl+bitmap split, bluestore Hybrid*).

All speak one contract: ``init_add_free``/``allocate``/``release``/
``get_free``; allocations never overlap, releases coalesce, and every
byte is conserved (model-checked in tests/test_blockstore.py).
"""

from __future__ import annotations

import bisect


class AllocError(Exception):
    """Not enough free space for the request (ENOSPC)."""


class BtreeAllocator:
    """Offset-sorted free extents + best-fit by size."""

    def __init__(self, alloc_unit: int = 4096) -> None:
        self.alloc_unit = alloc_unit
        self._offs: list[int] = []   # sorted extent start offsets
        self._lens: dict[int, int] = {}  # start -> length
        self.free_bytes = 0

    # -- free-space bookkeeping ----------------------------------------
    def init_add_free(self, offset: int, length: int) -> None:
        self.release([(offset, length)])

    def get_free(self) -> int:
        return self.free_bytes

    def free_extents(self) -> list[tuple[int, int]]:
        return [(o, self._lens[o]) for o in self._offs]

    # -- allocate -------------------------------------------------------
    def allocate(self, want: int, unit: int | None = None) -> list[tuple[int, int]]:
        """Up to ``want`` bytes (rounded up to alloc units) as one or
        more extents, best-fit first (smallest extent that satisfies
        the whole request; falls back to gathering largest-first)."""
        unit = unit or self.alloc_unit
        want = -(-want // unit) * unit
        if want > self.free_bytes:
            raise AllocError(f"want {want}, free {self.free_bytes}")
        # best fit: smallest single extent >= want
        best = None
        for off in self._offs:
            ln = self._lens[off]
            if ln >= want and (best is None or ln < self._lens[best]):
                best = off
        if best is not None:
            self._carve(best, want)
            return [(best, want)]
        # fragmented: gather largest-first until satisfied
        out: list[tuple[int, int]] = []
        remaining = want
        for off in sorted(self._offs, key=lambda o: -self._lens[o]):
            if remaining <= 0:
                break
            take = min(self._lens[off], remaining)
            take = (take // unit) * unit or min(self._lens[off], remaining)
            self._carve(off, take)
            out.append((off, take))
            remaining -= take
        if remaining > 0:  # conservation says this cannot happen
            self.release(out)
            raise AllocError(f"fragmentation shortfall: {remaining}")
        return out

    def _carve(self, off: int, take: int) -> None:
        ln = self._lens.pop(off)
        i = bisect.bisect_left(self._offs, off)
        self._offs.pop(i)
        if ln > take:
            rest = off + take
            bisect.insort(self._offs, rest)
            self._lens[rest] = ln - take
        self.free_bytes -= take

    # -- release --------------------------------------------------------
    def release(self, extents: list[tuple[int, int]]) -> None:
        for off, ln in extents:
            if ln <= 0:
                continue
            i = bisect.bisect_left(self._offs, off)
            # coalesce with predecessor
            if i > 0:
                p = self._offs[i - 1]
                pl = self._lens[p]
                if p + pl == off:
                    off, ln = p, pl + ln
                    self._offs.pop(i - 1)
                    del self._lens[p]
                    i -= 1
                elif p + pl > off:
                    raise ValueError(f"double free at {off:#x}")
            # coalesce with successor
            if i < len(self._offs):
                s = self._offs[i]
                if off + ln == s:
                    ln += self._lens.pop(s)
                    self._offs.pop(i)
                elif off + ln > s:
                    raise ValueError(f"double free at {off:#x}")
            bisect.insort(self._offs, off)
            self._lens[off] = ln
        # coalescing moved bytes between extents without changing the
        # total; the sum is the one invariant worth recomputing
        self.free_bytes = sum(self._lens.values())


class BitmapAllocator:
    """One bit per alloc unit; first-fit with a rolling cursor."""

    def __init__(self, alloc_unit: int = 4096) -> None:
        self.alloc_unit = alloc_unit
        self._free: bytearray = bytearray()  # 1 byte per unit (simple)
        self._base = 0
        self._cursor = 0
        self.free_bytes = 0

    def init_add_free(self, offset: int, length: int) -> None:
        unit = self.alloc_unit
        end_unit = (offset + length) // unit
        if len(self._free) < end_unit:
            self._free.extend(b"\0" * (end_unit - len(self._free)))
        self.release([(offset, length)])

    def get_free(self) -> int:
        return self.free_bytes

    def allocate(self, want: int, unit: int | None = None) -> list[tuple[int, int]]:
        u = self.alloc_unit
        want_units = -(-want // u)
        if want_units * u > self.free_bytes:
            raise AllocError(f"want {want}, free {self.free_bytes}")
        out: list[tuple[int, int]] = []
        remaining = want_units
        n = len(self._free)
        scanned = 0
        i = self._cursor
        run_start = -1
        while remaining > 0 and scanned <= n:
            if i >= n:
                if run_start >= 0:
                    take = min(i - run_start, remaining)
                    self._take(run_start, take, out)
                    remaining -= take
                    run_start = -1
                i = 0
                continue
            if self._free[i]:
                if run_start < 0:
                    run_start = i
                if i - run_start + 1 >= remaining:
                    # run already satisfies the request: stop scanning
                    self._take(run_start, remaining, out)
                    remaining = 0
                    i += 1
                    break
            else:
                if run_start >= 0:
                    take = min(i - run_start, remaining)
                    self._take(run_start, take, out)
                    remaining -= take
                    run_start = -1
            i += 1
            scanned += 1
        if run_start >= 0 and remaining > 0:
            take = min(i - run_start, remaining)
            self._take(run_start, take, out)
            remaining -= take
        if remaining > 0:
            self.release(out)
            raise AllocError("bitmap scan shortfall")
        self._cursor = i % max(n, 1)
        return out

    def _take(self, unit_off: int, units: int, out: list) -> None:
        u = self.alloc_unit
        for j in range(unit_off, unit_off + units):
            self._free[j] = 0
        self.free_bytes -= units * u
        off = unit_off * u
        if out and out[-1][0] + out[-1][1] == off:
            out[-1] = (out[-1][0], out[-1][1] + units * u)
        else:
            out.append((off, units * u))

    def release(self, extents: list[tuple[int, int]]) -> None:
        u = self.alloc_unit
        for off, ln in extents:
            if ln <= 0:
                continue
            assert off % u == 0 and ln % u == 0, (off, ln)
            for j in range(off // u, (off + ln) // u):
                if self._free[j]:
                    raise ValueError(f"double free at unit {j}")
                self._free[j] = 1
            self.free_bytes += ln

    def free_extents(self) -> list[tuple[int, int]]:
        out = []
        u = self.alloc_unit
        start = None
        for j, b in enumerate(self._free):
            if b and start is None:
                start = j
            elif not b and start is not None:
                out.append((start * u, (j - start) * u))
                start = None
        if start is not None:
            out.append((start * u, (len(self._free) - start) * u))
        return out


class HybridAllocator:
    """Btree until fragmentation explodes, bitmap spill after
    (HybridAvlAllocator: bounded btree memory, bitmap overflow)."""

    def __init__(self, alloc_unit: int = 4096, max_extents: int = 1024) -> None:
        self.alloc_unit = alloc_unit
        self.max_extents = max_extents
        self.btree = BtreeAllocator(alloc_unit)
        self.bitmap: BitmapAllocator | None = None
        self._device_end = 0

    def init_add_free(self, offset: int, length: int) -> None:
        self._device_end = max(self._device_end, offset + length)
        self.btree.init_add_free(offset, length)
        self._maybe_spill()

    def get_free(self) -> int:
        free = self.btree.get_free()
        if self.bitmap is not None:
            free += self.bitmap.get_free()
        return free

    def allocate(self, want: int, unit: int | None = None) -> list[tuple[int, int]]:
        u = unit or self.alloc_unit
        want = -(-want // u) * u
        if want > self.get_free():
            raise AllocError(f"want {want}, free {self.get_free()}")
        try:
            return self.btree.allocate(want, u)
        except AllocError:
            pass
        # gather across BOTH pools: total free covers the request even
        # when neither side alone does
        out: list[tuple[int, int]] = []
        remaining = want
        for pool in (self.btree, self.bitmap):
            if pool is None or remaining <= 0:
                continue
            take = min(remaining, (pool.get_free() // u) * u)
            if take <= 0:
                continue
            try:
                got = pool.allocate(take, u)
            except AllocError:
                continue
            out.extend(got)
            remaining -= sum(ln for _, ln in got)
        if remaining > 0:
            # return partial grabs to their pools via the btree (frees
            # flow to the btree; ownership transfers on release)
            self.btree.release(out)
            raise AllocError(f"hybrid shortfall: {remaining}")
        return out

    def release(self, extents: list[tuple[int, int]]) -> None:
        self.btree.release(extents)
        self._maybe_spill()

    def _maybe_spill(self) -> None:
        """Move the SMALLEST free extents into the bitmap child when
        the btree carries too many (bounded btree memory — the hybrid
        trade-off)."""
        if len(self.btree._offs) <= self.max_extents:
            return
        if self.bitmap is None:
            self.bitmap = BitmapAllocator(self.alloc_unit)
        # (re)size the child to the CURRENT device end: init_add_free
        # arrives incrementally and later spills may sit beyond the
        # end seen at first-spill time
        units = -(-self._device_end // self.alloc_unit)
        if len(self.bitmap._free) < units:
            self.bitmap._free.extend(
                b"\0" * (units - len(self.bitmap._free))
            )
        spill = sorted(
            self.btree.free_extents(), key=lambda e: e[1]
        )[: len(self.btree._offs) - self.max_extents // 2]
        for off, ln in spill:
            self.btree._carve(off, ln)
            self.bitmap.release([(off, ln)])

    def free_extents(self) -> list[tuple[int, int]]:
        out = self.btree.free_extents()
        if self.bitmap is not None:
            out += self.bitmap.free_extents()
        return sorted(out)


ALLOCATORS = {
    "btree": BtreeAllocator,
    "bitmap": BitmapAllocator,
    "hybrid": HybridAllocator,
}
