"""Native host runtime loader — builds and binds the C++ tier.

The reference keeps its hot host paths native (vendored SIMD GF
libraries, common/crc32c.cc dispatch, the OSD runtime); this package
is the analog: ``src/ceph_tpu_native.cc`` compiled on first use into a
shared library and bound via ctypes (no pybind11 in the image — plain
C ABI instead).

``available()`` gates every consumer: with no compiler the pure-Python
paths keep working, bit-identically (the native kernels are verified
against the Python oracles in tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "ceph_tpu_native.cc")
_BUILD_DIR = os.path.join(_HERE, "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libceph_tpu_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = [
        "g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
        _SRC, "-o", _LIB_PATH, "-pthread",
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired):
        return False
    if proc.returncode != 0:
        # -march=native can fail in exotic environments; retry plain.
        cmd.remove("-march=native")
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120
            )
        except (OSError, subprocess.TimeoutExpired):
            return False
    return proc.returncode == 0


def _bind(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ctpu_crc32c.restype = ctypes.c_uint32
    lib.ctpu_crc32c.argtypes = [ctypes.c_uint32, u8p, ctypes.c_size_t]
    lib.ctpu_xor_region.restype = None
    lib.ctpu_xor_region.argtypes = [u8p, u8p, ctypes.c_size_t]
    lib.ctpu_gf_mul_region.restype = None
    lib.ctpu_gf_mul_region.argtypes = [
        u8p, u8p, ctypes.c_size_t, ctypes.c_uint8, ctypes.c_int,
    ]
    lib.ctpu_gf_matrix_encode.restype = None
    lib.ctpu_gf_matrix_encode.argtypes = [
        ctypes.c_int, ctypes.c_int, u8p,
        ctypes.POINTER(u8p), ctypes.POINTER(u8p), ctypes.c_size_t,
    ]
    lib.ctpu_ring_create.restype = ctypes.c_void_p
    lib.ctpu_ring_create.argtypes = [ctypes.c_uint32, ctypes.c_uint32]
    lib.ctpu_ring_destroy.restype = None
    lib.ctpu_ring_destroy.argtypes = [ctypes.c_void_p]
    lib.ctpu_ring_close.restype = None
    lib.ctpu_ring_close.argtypes = [ctypes.c_void_p]
    lib.ctpu_ring_push.restype = ctypes.c_int
    lib.ctpu_ring_push.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_uint32, ctypes.c_int,
    ]
    lib.ctpu_ring_pop.restype = ctypes.c_int
    lib.ctpu_ring_pop.argtypes = [
        ctypes.c_void_p, u8p, ctypes.POINTER(ctypes.c_uint32), ctypes.c_int,
    ]
    lib.ctpu_ring_push_timed.restype = ctypes.c_int
    lib.ctpu_ring_push_timed.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_uint32, ctypes.c_int32,
    ]
    lib.ctpu_ring_pop_timed.restype = ctypes.c_int
    lib.ctpu_ring_pop_timed.argtypes = [
        ctypes.c_void_p, u8p, ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int32,
    ]
    lib.ctpu_ring_count.restype = ctypes.c_uint32
    lib.ctpu_ring_count.argtypes = [ctypes.c_void_p]
    lib.ctpu_ring_total_pushed.restype = ctypes.c_uint64
    lib.ctpu_ring_total_pushed.argtypes = [ctypes.c_void_p]
    # frame codec (msg/wire.py clear-mode hot path). c_char_p args are
    # zero-copy for Python bytes — no numpy round-trip per frame.
    lib.ctpu_crc32c_buf.restype = ctypes.c_uint32
    lib.ctpu_crc32c_buf.argtypes = [
        ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.ctpu_frame_encode.restype = ctypes.c_size_t
    lib.ctpu_frame_encode.argtypes = [
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint64, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
        u8p,
    ]
    lib.ctpu_frame_verify.restype = ctypes.c_int
    lib.ctpu_frame_verify.argtypes = [
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint64,
    ]


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("CEPH_TPU_NO_NATIVE"):
            return None
        src_mtime = os.path.getmtime(_SRC)
        stale = (
            not os.path.exists(_LIB_PATH)
            or os.path.getmtime(_LIB_PATH) < src_mtime
        )
        if stale and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _bind(lib)
        except OSError:
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _as_u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


# -- crc32c --------------------------------------------------------------
def crc32c(init: int, data) -> int:
    """Native crc32c (ceph_crc32c semantics); raises RuntimeError when
    the native library is unavailable — callers gate on available()."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    buf = np.frombuffer(bytes(data), dtype=np.uint8) \
        if not isinstance(data, np.ndarray) else np.ascontiguousarray(data)
    return lib.ctpu_crc32c(init & 0xFFFFFFFF, _as_u8p(buf), buf.size)


def crc32c_bytes(init: int, data) -> int:
    """Native crc32c over a bytes-like object, zero-copy for ``bytes``
    (no numpy round-trip — the wire hot-path entry). Semantics match
    :func:`crc32c` exactly: raw register in/out, no final xor."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    if not isinstance(data, bytes):
        data = bytes(data)
    return lib.ctpu_crc32c_buf(init & 0xFFFFFFFF, data, len(data))


# -- frame codec ---------------------------------------------------------
def frame_encode(msg_type: int, flags: int, seq: int, segments) -> bytes:
    """Assemble a clear-mode wire frame (header + segment table with
    per-segment crc32c + payloads) in one native call. ``segments`` is
    a sequence of bytes-like objects; compressed segments arrive
    pre-deflated. Bit-identical to the pure-Python wire.encode_frame
    clear path (pinned by tests/test_wire_native.py)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    segs = [s if isinstance(s, bytes) else bytes(s) for s in segments]
    nseg = len(segs)
    total = 16 + nseg * 8 + sum(len(s) for s in segs)
    out = bytearray(total)
    ptrs = (ctypes.c_char_p * nseg)(*segs)
    lens = (ctypes.c_uint64 * nseg)(*[len(s) for s in segs])
    written = lib.ctpu_frame_encode(
        msg_type, flags, seq, nseg, ptrs, lens,
        (ctypes.c_uint8 * total).from_buffer(out),
    )
    if written != total:
        raise RuntimeError(
            f"frame encode size mismatch: {written} != {total}"
        )
    return bytes(out)


def frame_verify(table, payload) -> int:
    """Batch-verify per-segment CRCs of a received clear frame. Returns
    -1 when all segments match, -2 on a length/table mismatch, else the
    index of the first bad segment."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    if not isinstance(table, bytes):
        table = bytes(table)
    if not isinstance(payload, bytes):
        payload = bytes(payload)
    return lib.ctpu_frame_verify(table, len(table) // 8, payload, len(payload))


# -- GF region ops -------------------------------------------------------
def xor_region(dst: np.ndarray, src: np.ndarray) -> None:
    lib = _load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    assert dst.size == src.size and dst.dtype == np.uint8
    lib.ctpu_xor_region(_as_u8p(dst), _as_u8p(src), dst.size)


def gf_mul_region(
    dst: np.ndarray, src: np.ndarray, c: int, accumulate: bool = False
) -> None:
    lib = _load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    assert dst.size == src.size and dst.dtype == np.uint8
    lib.ctpu_gf_mul_region(
        _as_u8p(dst), _as_u8p(src), dst.size, c, int(accumulate)
    )


def gf_matrix_encode(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """parity[m, n] = matrix[m, k] x data[k, n] over GF(2^8) — the host
    encode path (jerasure_matrix_encode / ec_encode_data analog)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    m, k = matrix.shape
    assert data.shape[0] == k, (data.shape, k)
    n = data.shape[1]
    parity = np.zeros((m, n), dtype=np.uint8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    data_ptrs = (u8p * k)(*[_as_u8p(data[i]) for i in range(k)])
    parity_ptrs = (u8p * m)(*[_as_u8p(parity[j]) for j in range(m)])
    lib.ctpu_gf_matrix_encode(
        k, m, _as_u8p(matrix), data_ptrs, parity_ptrs, n
    )
    return parity


# -- ring buffer ---------------------------------------------------------
class RingBuffer:
    """Blocking MPMC ring of fixed-size slots (native storage) — the
    host staging queue feeding device batches."""

    def __init__(self, capacity: int, slot_bytes: int) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._ring = lib.ctpu_ring_create(capacity, slot_bytes)
        if not self._ring:
            raise MemoryError("ring allocation failed")
        self.capacity = capacity
        self.slot_bytes = slot_bytes

    def push(self, data, blocking: bool = True) -> bool:
        buf = np.frombuffer(bytes(data), dtype=np.uint8) \
            if not isinstance(data, np.ndarray) else np.ascontiguousarray(data)
        rc = self._lib.ctpu_ring_push(
            self._ring, _as_u8p(buf), buf.size, int(blocking)
        )
        if rc < 0:
            raise ValueError(
                f"slot overflow: {buf.size} > {self.slot_bytes}"
            )
        return rc == 1

    def pop(self, blocking: bool = True) -> bytes | None:
        out = np.empty(self.slot_bytes, dtype=np.uint8)
        ln = ctypes.c_uint32()
        rc = self._lib.ctpu_ring_pop(
            self._ring, _as_u8p(out), ctypes.byref(ln), int(blocking)
        )
        if rc != 1:
            return None
        return out[: ln.value].tobytes()

    def push_timed(self, data, timeout: "float | None" = None) -> int:
        """Push with a bounded wait: 1 = pushed, 0 = ring closed,
        -2 = timed out (timeout is seconds; None waits forever)."""
        if not isinstance(data, bytes):
            data = bytes(data)
        ms = -1 if timeout is None else max(0, int(timeout * 1000))
        # zero-copy view of the bytes object (c_char_p cast, no staging
        # copy — the C side memcpys straight into the slot)
        ptr = ctypes.cast(
            ctypes.c_char_p(data), ctypes.POINTER(ctypes.c_uint8)
        )
        rc = self._lib.ctpu_ring_push_timed(self._ring, ptr, len(data), ms)
        if rc == -1:
            raise ValueError(
                f"slot overflow: {len(data)} > {self.slot_bytes}"
            )
        return rc

    def pop_timed(self, timeout: "float | None" = None):
        """Pop with a bounded wait: (1, chunk) on success, (0, None)
        when the ring is closed and drained, (-2, None) on timeout."""
        ms = -1 if timeout is None else max(0, int(timeout * 1000))
        out = bytearray(self.slot_bytes)
        ln = ctypes.c_uint32()
        rc = self._lib.ctpu_ring_pop_timed(
            self._ring,
            (ctypes.c_uint8 * self.slot_bytes).from_buffer(out),
            ctypes.byref(ln),
            ms,
        )
        if rc != 1:
            return rc, None
        return 1, bytes(out[: ln.value])

    def close(self) -> None:
        self._lib.ctpu_ring_close(self._ring)

    def __len__(self) -> int:
        return self._lib.ctpu_ring_count(self._ring)

    @property
    def total_pushed(self) -> int:
        return self._lib.ctpu_ring_total_pushed(self._ring)

    def __del__(self) -> None:
        ring = getattr(self, "_ring", None)
        if ring:
            self._lib.ctpu_ring_destroy(ring)
            self._ring = None
