// Native host runtime for ceph_tpu — the C++ tier the reference keeps
// in vendored SIMD libraries and the OSD runtime (SURVEY.md §2.4):
//
//  * crc32c: slicing-by-8 software kernel with an SSE4.2 hardware path
//    (the ceph_crc32c dispatch analog, src/common/crc32c.cc) — raw
//    register in/out, reflected Castagnoli, no final xor, bit-exact
//    with the Python oracle (checksum/reference.crc32c_ref).
//  * GF(2^8) region ops over the 0x11D field: constant-multiply /
//    xor-accumulate regions and a full matrix encode — the
//    jerasure/ISA-L region-op analog used for host-side staging,
//    verification, and small low-latency fallback paths.
//  * A blocking MPMC ring buffer of fixed slots — the host staging
//    queue of the dispatch pipeline (host ring -> pinned staging ->
//    device batches; SURVEY.md §7 step 4).
//
// Plain C ABI so ctypes loads it with no binding generator.

#include <chrono>
#include <cstdint>
#include <cstring>
#include <condition_variable>
#include <mutex>
#include <new>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

extern "C" {

// ---------------------------------------------------------------- crc32c
static uint32_t crc_table[8][256];
static bool crc_init_done = false;

static void crc_init() {
    if (crc_init_done) return;
    const uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
        crc_table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = crc_table[0][i];
        for (int t = 1; t < 8; t++) {
            c = crc_table[0][c & 0xFF] ^ (c >> 8);
            crc_table[t][i] = c;
        }
    }
    crc_init_done = true;
}

uint32_t ctpu_crc32c(uint32_t crc, const uint8_t* data, size_t len) {
#if defined(__SSE4_2__)
    // Hardware CRC32C (the ceph_crc32c_intel_fast analog).
    while (len >= 8 && (reinterpret_cast<uintptr_t>(data) & 7)) {
        crc = _mm_crc32_u8(crc, *data++);
        len--;
    }
    uint64_t c64 = crc;
    while (len >= 8) {
        c64 = _mm_crc32_u64(c64, *reinterpret_cast<const uint64_t*>(data));
        data += 8;
        len -= 8;
    }
    crc = static_cast<uint32_t>(c64);
    while (len--) crc = _mm_crc32_u8(crc, *data++);
    return crc;
#else
    crc_init();
    // slicing-by-8
    while (len >= 8) {
        uint32_t lo;
        std::memcpy(&lo, data, 4);
        lo ^= crc;
        uint32_t hi;
        std::memcpy(&hi, data + 4, 4);
        crc = crc_table[7][lo & 0xFF] ^ crc_table[6][(lo >> 8) & 0xFF] ^
              crc_table[5][(lo >> 16) & 0xFF] ^ crc_table[4][lo >> 24] ^
              crc_table[3][hi & 0xFF] ^ crc_table[2][(hi >> 8) & 0xFF] ^
              crc_table[1][(hi >> 16) & 0xFF] ^ crc_table[0][hi >> 24];
        data += 8;
        len -= 8;
    }
    while (len--) crc = crc_table[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
    return crc;
#endif
}

// ------------------------------------------------------------- GF(2^8)
// 0x11D field, matching ceph_tpu.gf.tables (the jerasure/ISA-L field).
static uint8_t gf_mul_table[256][256];
static bool gf_init_done = false;

static void gf_init() {
    if (gf_init_done) return;
    uint8_t exp_t[512];
    int log_t[256];
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp_t[i] = static_cast<uint8_t>(x);
        log_t[x] = i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11D;
    }
    for (int i = 255; i < 512; i++) exp_t[i] = exp_t[i - 255];
    for (int a = 0; a < 256; a++) {
        gf_mul_table[0][a] = 0;
        gf_mul_table[a][0] = 0;
    }
    for (int a = 1; a < 256; a++)
        for (int b = 1; b < 256; b++)
            gf_mul_table[a][b] = exp_t[log_t[a] + log_t[b]];
    gf_init_done = true;
}

void ctpu_xor_region(uint8_t* dst, const uint8_t* src, size_t n) {
    // 64-bit wide XOR; compilers vectorize this loop.
    size_t i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t a, b;
        std::memcpy(&a, dst + i, 8);
        std::memcpy(&b, src + i, 8);
        a ^= b;
        std::memcpy(dst + i, &a, 8);
    }
    for (; i < n; i++) dst[i] ^= src[i];
}

void ctpu_gf_mul_region(uint8_t* dst, const uint8_t* src, size_t n,
                        uint8_t c, int accumulate) {
    gf_init();
    const uint8_t* row = gf_mul_table[c];
    if (accumulate)
        for (size_t i = 0; i < n; i++) dst[i] ^= row[src[i]];
    else
        for (size_t i = 0; i < n; i++) dst[i] = row[src[i]];
}

// matrix: [m][k] row-major GF coefficients; data/parity: arrays of
// pointers to len-byte regions. parity[j] = sum_i matrix[j][i]*data[i].
void ctpu_gf_matrix_encode(int k, int m, const uint8_t* matrix,
                           const uint8_t* const* data,
                           uint8_t* const* parity, size_t len) {
    gf_init();
    for (int j = 0; j < m; j++) {
        std::memset(parity[j], 0, len);
        for (int i = 0; i < k; i++) {
            uint8_t c = matrix[j * k + i];
            if (c == 0) continue;
            if (c == 1)
                ctpu_xor_region(parity[j], data[i], len);
            else
                ctpu_gf_mul_region(parity[j], data[i], len, c, 1);
        }
    }
}

// ---------------------------------------------------------- ring buffer
struct Ring {
    uint32_t capacity;
    uint32_t slot_bytes;
    uint32_t head = 0;   // next pop
    uint32_t tail = 0;   // next push
    uint32_t count = 0;
    uint64_t total_pushed = 0;
    bool closed = false;
    uint8_t* slots;
    uint32_t* lens;
    std::mutex mu;
    std::condition_variable not_full, not_empty;
};

void* ctpu_ring_create(uint32_t capacity, uint32_t slot_bytes) {
    if (capacity == 0 || slot_bytes == 0) return nullptr;
    Ring* r = new (std::nothrow) Ring();
    if (!r) return nullptr;
    r->capacity = capacity;
    r->slot_bytes = slot_bytes;
    r->slots = new (std::nothrow) uint8_t[size_t(capacity) * slot_bytes];
    r->lens = new (std::nothrow) uint32_t[capacity];
    if (!r->slots || !r->lens) {
        delete[] r->slots;
        delete[] r->lens;
        delete r;
        return nullptr;
    }
    return r;
}

void ctpu_ring_destroy(void* h) {
    Ring* r = static_cast<Ring*>(h);
    if (!r) return;
    delete[] r->slots;
    delete[] r->lens;
    delete r;
}

void ctpu_ring_close(void* h) {
    Ring* r = static_cast<Ring*>(h);
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
    r->not_empty.notify_all();
    r->not_full.notify_all();
}

// returns 1 on success, 0 if full (non-blocking) or closed, -1 bad args
int ctpu_ring_push(void* h, const uint8_t* data, uint32_t len,
                   int blocking) {
    Ring* r = static_cast<Ring*>(h);
    if (!r || len > r->slot_bytes) return -1;
    std::unique_lock<std::mutex> lk(r->mu);
    if (blocking)
        r->not_full.wait(lk, [r] { return r->count < r->capacity || r->closed; });
    if (r->closed || r->count == r->capacity) return 0;
    std::memcpy(r->slots + size_t(r->tail) * r->slot_bytes, data, len);
    r->lens[r->tail] = len;
    r->tail = (r->tail + 1) % r->capacity;
    r->count++;
    r->total_pushed++;
    r->not_empty.notify_one();
    return 1;
}

// returns 1 on success (len written), 0 if empty/closed, -1 bad args
int ctpu_ring_pop(void* h, uint8_t* out, uint32_t* len, int blocking) {
    Ring* r = static_cast<Ring*>(h);
    if (!r || !out || !len) return -1;
    std::unique_lock<std::mutex> lk(r->mu);
    if (blocking)
        r->not_empty.wait(lk, [r] { return r->count > 0 || r->closed; });
    if (r->count == 0) return 0;
    std::memcpy(out, r->slots + size_t(r->head) * r->slot_bytes,
                r->lens[r->head]);
    *len = r->lens[r->head];
    r->head = (r->head + 1) % r->capacity;
    r->count--;
    r->not_full.notify_one();
    return 1;
}

// Timed variants for transport use (msg/shm_ring.py): wait up to
// timeout_ms (negative = forever). Returns 1 on success, 0 when the
// ring is closed (push) / closed and drained (pop), -2 on timeout,
// -1 on bad args. A closed ring still drains buffered slots — the
// byte-stream EOF contract a half-closed TCP socket provides.
int ctpu_ring_push_timed(void* h, const uint8_t* data, uint32_t len,
                         int32_t timeout_ms) {
    Ring* r = static_cast<Ring*>(h);
    if (!r || len > r->slot_bytes) return -1;
    std::unique_lock<std::mutex> lk(r->mu);
    auto ready = [r] { return r->count < r->capacity || r->closed; };
    if (timeout_ms < 0) {
        r->not_full.wait(lk, ready);
    } else if (!r->not_full.wait_for(
                   lk, std::chrono::milliseconds(timeout_ms), ready)) {
        return -2;
    }
    if (r->closed) return 0;
    std::memcpy(r->slots + size_t(r->tail) * r->slot_bytes, data, len);
    r->lens[r->tail] = len;
    r->tail = (r->tail + 1) % r->capacity;
    r->count++;
    r->total_pushed++;
    r->not_empty.notify_one();
    return 1;
}

int ctpu_ring_pop_timed(void* h, uint8_t* out, uint32_t* len,
                        int32_t timeout_ms) {
    Ring* r = static_cast<Ring*>(h);
    if (!r || !out || !len) return -1;
    std::unique_lock<std::mutex> lk(r->mu);
    auto ready = [r] { return r->count > 0 || r->closed; };
    if (timeout_ms < 0) {
        r->not_empty.wait(lk, ready);
    } else if (!r->not_empty.wait_for(
                   lk, std::chrono::milliseconds(timeout_ms), ready)) {
        return -2;
    }
    if (r->count == 0) return 0;
    std::memcpy(out, r->slots + size_t(r->head) * r->slot_bytes,
                r->lens[r->head]);
    *len = r->lens[r->head];
    r->head = (r->head + 1) % r->capacity;
    r->count--;
    r->not_full.notify_one();
    return 1;
}

uint32_t ctpu_ring_count(void* h) {
    Ring* r = static_cast<Ring*>(h);
    std::lock_guard<std::mutex> lk(r->mu);
    return r->count;
}

uint64_t ctpu_ring_total_pushed(void* h) {
    Ring* r = static_cast<Ring*>(h);
    std::lock_guard<std::mutex> lk(r->mu);
    return r->total_pushed;
}

// ----------------------------------------------------------- frame codec
// msg/wire.py hot-path analog (the reference's msgr2 frame assembly,
// src/msg/async/frames_v2.cc): clear-mode frames only — a 16-byte
// little-endian header (magic "CTv2", u16 msg_type, u8 flags, u8 nseg,
// u64 seq), an nseg x (u32 len, u32 crc32c) segment table, then the
// concatenated payloads. CRCs are seeded 0xFFFFFFFF per segment
// (wire.CRC_SEED), matching the Python path bit-for-bit. Compressed
// segments arrive pre-deflated (the zlib step stays in Python); secure
// frames never reach this path.

// zero-copy crc32c entry for Python bytes (no numpy round-trip).
uint32_t ctpu_crc32c_buf(uint32_t crc, const char* data, size_t len) {
    return ctpu_crc32c(crc, reinterpret_cast<const uint8_t*>(data), len);
}

// Assemble header + table + payloads into `out` (caller sizes it as
// 16 + nseg*8 + sum(lens)). Returns total bytes written.
size_t ctpu_frame_encode(uint32_t msg_type, uint32_t flags, uint64_t seq,
                         uint32_t nseg, const char* const* segs,
                         const uint64_t* lens, uint8_t* out) {
    uint8_t* p = out;
    p[0] = 'C'; p[1] = 'T'; p[2] = 'v'; p[3] = '2';
    p[4] = msg_type & 0xFF; p[5] = (msg_type >> 8) & 0xFF;
    p[6] = flags & 0xFF;
    p[7] = nseg & 0xFF;
    for (int b = 0; b < 8; b++) p[8 + b] = (seq >> (8 * b)) & 0xFF;
    p += 16;
    uint8_t* table = p;
    p += size_t(nseg) * 8;
    for (uint32_t i = 0; i < nseg; i++) {
        const uint8_t* seg = reinterpret_cast<const uint8_t*>(segs[i]);
        uint64_t len = lens[i];
        uint32_t crc = ctpu_crc32c(0xFFFFFFFFu, seg, len);
        for (int b = 0; b < 4; b++)
            table[i * 8 + b] = (len >> (8 * b)) & 0xFF;
        for (int b = 0; b < 4; b++)
            table[i * 8 + 4 + b] = (crc >> (8 * b)) & 0xFF;
        std::memcpy(p, seg, len);
        p += len;
    }
    return static_cast<size_t>(p - out);
}

// Batch-verify the per-segment CRCs of a received clear frame:
// `table` is the raw nseg*8-byte little-endian (len, crc) entries,
// `payload` the concatenated segment bytes. Returns -1 when every
// segment matches, -2 when the table lengths disagree with
// payload_len, else the index of the first mismatching segment.
int ctpu_frame_verify(const char* table_c, uint32_t nseg,
                      const char* payload_c, uint64_t payload_len) {
    const uint8_t* table = reinterpret_cast<const uint8_t*>(table_c);
    const uint8_t* payload = reinterpret_cast<const uint8_t*>(payload_c);
    uint64_t off = 0;
    for (uint32_t i = 0; i < nseg; i++) {
        uint32_t len = 0, want = 0;
        for (int b = 0; b < 4; b++)
            len |= static_cast<uint32_t>(table[i * 8 + b]) << (8 * b);
        for (int b = 0; b < 4; b++)
            want |= static_cast<uint32_t>(table[i * 8 + 4 + b]) << (8 * b);
        if (off + len > payload_len) return -2;
        uint32_t got = ctpu_crc32c(0xFFFFFFFFu, payload + off, len);
        if (got != want) return static_cast<int>(i);
        off += len;
    }
    if (off != payload_len) return -2;
    return -1;
}

}  // extern "C"
