"""AsyncReserver — bounded background-work slots with priority
queueing (the reference's common/AsyncReserver.h, used by the OSD as
``local_reserver``/``remote_reserver`` to gate backfill concurrency
per the backfill_reservation.rst protocol).

Each OSD grants at most ``max_allowed()`` concurrent reservations;
further requests queue by (priority desc, arrival order) and are
granted as slots free up. Grants fire the request's callback on the
releasing thread (callbacks must be cheap/queue-flipping — the
reference schedules a Context the same way)."""

from __future__ import annotations

import itertools
import threading
from collections.abc import Callable


class AsyncReserver:
    def __init__(self, max_allowed: Callable[[], int]) -> None:
        self._max = max_allowed
        self._lock = threading.Lock()
        self._held: set = set()
        #: queued: key -> (prio, seq, grant_cb)
        self._queued: dict = {}
        self._seq = itertools.count()

    def request(self, key, prio: int, grant_cb: Callable[[], None]) -> None:
        """Queue a reservation; ``grant_cb`` fires (possibly
        immediately, on this thread) when a slot is granted.

        Re-requesting is IDEMPOTENT-WITH-REGRANT, not a no-op: a key
        already held fires the new callback immediately, and a queued
        key's callback is REPLACED (keeping its arrival order). Over
        RPC this matters: a requester that timed out and retries
        sends a fresh tid — its old callback would answer a dead
        request, wedging the slot forever (round-5 review finding)."""
        grant = False
        with self._lock:
            if key in self._held:
                grant = True
            elif key in self._queued:
                prio0, seq0, _stale = self._queued[key]
                self._queued[key] = (prio0, seq0, grant_cb)
            elif len(self._held) < max(1, self._max()):
                self._held.add(key)
                grant = True
            else:
                self._queued[key] = (prio, next(self._seq), grant_cb)
        if grant:
            grant_cb()

    def cancel(self, key) -> None:
        """Withdraw a queued OR held reservation (release semantics
        for held keys: the next queued request gets the slot)."""
        self.release(key)

    def release(self, key) -> None:
        grants: list[Callable[[], None]] = []
        with self._lock:
            self._queued.pop(key, None)
            self._held.discard(key)
            while self._queued and len(self._held) < max(1, self._max()):
                next_key = min(
                    self._queued,
                    key=lambda k: (-self._queued[k][0], self._queued[k][1]),
                )
                _prio, _seq, cb = self._queued.pop(next_key)
                self._held.add(next_key)
                grants.append(cb)
        for cb in grants:
            cb()

    def held(self) -> int:
        with self._lock:
            return len(self._held)

    def queued(self) -> int:
        with self._lock:
            return len(self._queued)

    def has(self, key) -> bool:
        with self._lock:
            return key in self._held
