"""Admin command surface — the admin_socket / ``ceph tell`` analog.

The reference exposes runtime introspection and control through a unix
socket (common/admin_socket.cc): ``perf dump``, ``config show``/
``config set``, ``dump_historic_ops``, and the EC error-inject tell
commands. Here the same registry is an in-process command table (the
transport is trivial to add; every consumer in-tree is in-process).

Built-in commands are registered at import: perf/config/trace plus the
ECInject operator surface (the qa suites drive injection exactly this
way — qa/tasks/ceph_manager.py uses `ceph tell osd.N injectargs`).
"""

from __future__ import annotations

import threading
from collections.abc import Callable


class AdminSocket:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._commands: dict[str, tuple[Callable[..., object], str]] = {}

    def register(self, command: str, fn: Callable[..., object], desc: str = "") -> None:
        with self._lock:
            if command in self._commands:
                raise ValueError(f"command {command!r} already registered")
            self._commands[command] = (fn, desc)

    def unregister(self, command: str) -> None:
        with self._lock:
            self._commands.pop(command, None)

    def execute(self, command: str, **kwargs):
        with self._lock:
            entry = self._commands.get(command)
        if entry is None:
            raise KeyError(f"unknown admin command {command!r}")
        return entry[0](**kwargs)

    def help(self) -> dict[str, str]:
        with self._lock:
            return {cmd: desc for cmd, (_, desc) in sorted(self._commands.items())}


admin_socket = AdminSocket()


def _register_builtins() -> None:
    from ceph_tpu.utils.config import config
    from ceph_tpu.utils.perf_counters import perf_collection
    from ceph_tpu.utils.trace import tracer

    admin_socket.register(
        "perf dump", lambda: perf_collection.dump(),
        "dump all perf counters",
    )
    admin_socket.register(
        "config show", lambda: config.show(),
        "effective config values with their source layer",
    )
    admin_socket.register(
        "config set",
        lambda name, value: (config.set(name, value), config.get(name))[1],
        "set a runtime config override",
    )
    admin_socket.register(
        "config get", lambda name: config.get(name),
        "read one effective config value",
    )
    admin_socket.register(
        "dump_historic_ops",
        lambda limit=None: tracer.dump_historic(limit),
        "recently completed trace spans",
    )

    def _inject(kind: str):
        from ceph_tpu.pipeline.inject import ANY_SHARD, ec_inject

        fn = getattr(ec_inject, kind)

        def run(oid, type, when=0, duration=1, shard=ANY_SHARD):
            return fn(oid, int(type), when=int(when),
                      duration=int(duration), shard=int(shard))

        return run

    admin_socket.register(
        "injectecreaderr", _inject("read_error"),
        "inject EC read errors (type 0=EIO, 1=missing)",
    )
    admin_socket.register(
        "injectecwriteerr", _inject("write_error"),
        "inject EC write errors (type 0=abort, 1=dropped sub-write)",
    )

    def _clear(kind: str):
        from ceph_tpu.pipeline.inject import ANY_SHARD, ec_inject

        fn = getattr(ec_inject, kind)

        def run(oid, type, shard=ANY_SHARD):
            return fn(oid, int(type), shard=int(shard))

        return run

    admin_socket.register(
        "injectecclearreaderr", _clear("clear_read_error"),
        "clear injected EC read errors",
    )
    admin_socket.register(
        "injectecclearwriteerr", _clear("clear_write_error"),
        "clear injected EC write errors",
    )


_register_builtins()
