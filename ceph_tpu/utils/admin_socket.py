"""Admin command surface — the admin_socket / ``ceph tell`` analog.

The reference exposes runtime introspection and control through a unix
socket (common/admin_socket.cc): ``perf dump``, ``config show``/
``config set``, ``dump_historic_ops``, and the EC error-inject tell
commands. Here the same registry is an in-process command table (the
transport is trivial to add; every consumer in-tree is in-process).

Built-in commands (perf/config/trace plus the ECInject operator
surface — the qa suites drive injection exactly this way,
qa/tasks/ceph_manager.py `ceph tell osd.N injectargs`) register
lazily on first socket use so that importing ceph_tpu never touches
jax: the driver's virtual-mesh dryrun must configure the backend
before anything initializes it.
"""

from __future__ import annotations

import threading
from collections.abc import Callable


class AdminSocket:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._commands: dict[str, tuple[Callable[..., object], str]] = {}
        self._builtin_lock = threading.RLock()
        self._builtins_done = False
        self._builtins_registering = False

    def _ensure_builtins(self) -> None:
        # Builtins register on first use, not at import: the registration
        # pulls in ceph_tpu.pipeline, and `import ceph_tpu` must stay free
        # of jax backend initialization for the multichip dryrun. The
        # dedicated RLock makes concurrent first users wait for the full
        # table while the builtins' own register() calls re-enter; the
        # done-flag flips only after success so a transient failure
        # retries on the next call.
        with self._builtin_lock:
            if self._builtins_done or self._builtins_registering:
                return
            self._builtins_registering = True
            try:
                _register_builtins(self)
                self._builtins_done = True
            finally:
                self._builtins_registering = False

    def register(self, command: str, fn: Callable[..., object], desc: str = "") -> None:
        self._ensure_builtins()
        with self._lock:
            if command in self._commands:
                raise ValueError(f"command {command!r} already registered")
            self._commands[command] = (fn, desc)

    def unregister(self, command: str) -> None:
        # Builtins load first so an unregister sticks: a later first
        # execute() must not resurrect what the caller removed.
        self._ensure_builtins()
        with self._lock:
            self._commands.pop(command, None)

    def execute(self, command: str, **kwargs):
        self._ensure_builtins()
        with self._lock:
            entry = self._commands.get(command)
        if entry is None:
            raise KeyError(f"unknown admin command {command!r}")
        return entry[0](**kwargs)

    def help(self) -> dict[str, str]:
        self._ensure_builtins()
        with self._lock:
            return {cmd: desc for cmd, (_, desc) in sorted(self._commands.items())}


admin_socket = AdminSocket()


def _register_builtins(sock: AdminSocket) -> None:
    from ceph_tpu.utils.config import config
    from ceph_tpu.utils.perf_counters import perf_collection
    from ceph_tpu.utils.platform import install_debug_observer
    from ceph_tpu.utils.trace import tracer

    # `config set debug_nan_check true` over the admin socket flips
    # the jax debug flags live (sanitizer-toggle analog, SURVEY §5.2)
    install_debug_observer()

    sock.register(
        "perf dump", lambda: perf_collection.dump(),
        "dump all perf counters",
    )
    sock.register(
        "config show", lambda: config.show(),
        "effective config values with their source layer",
    )
    sock.register(
        "config set",
        lambda name, value: (config.set(name, value), config.get(name))[1],
        "set a runtime config override",
    )
    sock.register(
        "config get", lambda name: config.get(name),
        "read one effective config value",
    )
    sock.register(
        "dump_historic_ops",
        lambda limit=None: tracer.dump_historic(limit),
        "recently completed trace spans",
    )

    from ceph_tpu.utils.cluster_log import cluster_log
    from ceph_tpu.utils.optracker import op_tracker

    sock.register(
        "dump_ops_in_flight",
        lambda daemon=None: op_tracker.dump_ops_in_flight(daemon),
        "live tracked ops, oldest first, with event timelines",
    )
    sock.register(
        "perf reset",
        lambda name=None: perf_collection.reset(name),
        "zero one named counter set, or all of them",
    )

    from ceph_tpu.utils import lockdep

    sock.register(
        "lockdep", lambda: lockdep.dump(),
        "lock-dependency graph + findings (order-inversion cycles, "
        "rank violations, blocking-under-lock sites) from the "
        "runtime lockdep detector",
    )
    # (the "pgmap" command registers from cluster/pgmap.py at its own
    # import — the admin surface must not reach UP into the cluster
    # tier; ECLint EC101 pins the layering)

    sock.register(
        "log last",
        lambda n=20, daemon=None, severity=None: cluster_log.last(
            int(n), daemon, severity
        ),
        "recent cluster-log events (the ceph.log / `ceph log last` "
        "analog; severity filters at-or-above)",
    )

    from ceph_tpu.utils.log import root_log

    sock.register(
        "log dump",
        lambda reason="admin": root_log.dump_recent(reason),
        "dump the ring of recent (gathered) log entries",
    )
    sock.register(
        "log flush", lambda: root_log.flush(),
        "flush queued log entries to the sink",
    )
    sock.register(
        "log set",
        lambda subsys, level, gather=None: (
            root_log.set_level(
                subsys, int(level),
                None if gather is None else int(gather),
            ),
            root_log.dump_levels().get(subsys),
        )[1],
        "set a subsystem's log/gather levels (debug_<subsys> analog)",
    )
    sock.register(
        "log levels", lambda: root_log.dump_levels(),
        "per-subsystem log/gather level pairs",
    )

    def _inject(kind: str):
        def run(oid, type, when=0, duration=1, shard=None):
            from ceph_tpu.pipeline.inject import ANY_SHARD, ec_inject

            fn = getattr(ec_inject, kind)
            return fn(oid, int(type), when=int(when), duration=int(duration),
                      shard=ANY_SHARD if shard is None else int(shard))

        return run

    sock.register(
        "injectecreaderr", _inject("read_error"),
        "inject EC read errors (type 0=EIO, 1=missing)",
    )
    sock.register(
        "injectecwriteerr", _inject("write_error"),
        "inject EC write errors (type 0=abort, 1=dropped sub-write)",
    )

    def _clear(kind: str):
        def run(oid, type, shard=None):
            from ceph_tpu.pipeline.inject import ANY_SHARD, ec_inject

            fn = getattr(ec_inject, kind)
            return fn(oid, int(type),
                      shard=ANY_SHARD if shard is None else int(shard))

        return run

    sock.register(
        "injectecclearreaderr", _clear("clear_read_error"),
        "clear injected EC read errors",
    )
    sock.register(
        "injectecclearwriteerr", _clear("clear_write_error"),
        "clear injected EC write errors",
    )
