"""Prometheus-style metrics exporter — the src/exporter/ +
pybind/mgr/prometheus analog.

The reference exposes every daemon's PerfCounters in the Prometheus
text exposition format, either from the mgr prometheus module or the
standalone ceph-exporter scraping admin sockets. Here one HTTP
endpoint renders the process-global ``perf_collection`` (every
pipeline/daemon counter set registers there) the same way:

- U64 counters      -> ``counter``
- gauges            -> ``gauge``
- time accumulators -> ``counter`` (seconds, ``_seconds`` suffix)
- averages          -> ``_sum`` + ``_count`` (an untyped summary)
- histograms        -> ``_bucket{le=...}`` cumulative + ``_count``
                       + ``_sum``

Metric name = ``ceph_tpu_<key>``; the owning counter-set's name rides
in a ``set`` label (the reference labels by daemon the same way, e.g.
``ceph_osd_op_w{ceph_daemon="osd.0"}``). Set names containing a
``.pool.<name>`` segment split into a ``set`` + ``pool`` label pair
(``objecter.pool.mypool`` -> ``set="objecter",pool="mypool"``), so
per-pool accounting — the objecter's per-pool op/byte sets, the
PGMap's per-pool gauges — lands as a proper Prometheus dimension. The server is a stdlib
ThreadingHTTPServer on a background thread serving ``/metrics`` —
curl-able in a vstart cluster (``ceph_tpu.cli vstart --exporter``).
"""

from __future__ import annotations

import http.server
import threading

from .perf_counters import CounterType, PerfCountersCollection
from .perf_counters import perf_collection as _global_collection

_PREFIX = "ceph_tpu"


def _sanitize(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    return s if not s[:1].isdigit() else "_" + s


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n"
    )


def render_exposition(
    collection: PerfCountersCollection | None = None,
) -> str:
    """Render every registered counter set in text exposition format
    (one scrape = one consistent dump per set)."""
    coll = collection if collection is not None else _global_collection
    # metric -> (type string, [(labels, value), ...])
    metrics: dict[str, tuple[str, list[tuple[str, object]]]] = {}

    def emit(metric: str, typ: str, labels: str, value) -> None:
        entry = metrics.setdefault(metric, (typ, []))
        entry[1].append((labels, value))

    for set_name, (schema, dump) in coll.snapshot().items():
        # a trailing ".pool.<name>" segment becomes a pool label —
        # only when <name> is the final dot-free component, so the
        # per-PG pipeline sets ("osd.0.<pool>.<pg>.rmw", where a pool
        # may legitimately be NAMED "pool") keep their plain label
        base, sep, pool = set_name.rpartition(".pool.")
        if sep and pool and "." not in pool:
            label = (
                f'pool="{_escape_label(pool)}",'
                f'set="{_escape_label(base)}"'
            )
        else:
            label = f'set="{_escape_label(set_name)}"'
        for key, spec in schema.items():
            metric = f"{_PREFIX}_{_sanitize(key)}"
            v = dump[key]
            t = spec["type"]
            if t is CounterType.U64:
                emit(metric, "counter", label, v)
            elif t is CounterType.GAUGE:
                emit(metric, "gauge", label, v)
            elif t is CounterType.TIME:
                emit(f"{metric}_seconds", "counter", label, v)
            elif t is CounterType.AVG:
                emit(f"{metric}_sum", "untyped", label, v["sum"])
                emit(f"{metric}_count", "untyped", label, v["avgcount"])
            elif t is CounterType.HISTOGRAM:
                cum = 0
                for bound, count in zip(
                    v["buckets"], v["counts"][:-1]
                ):
                    cum += count
                    emit(
                        f"{metric}_bucket", "untyped",
                        f'{label},le="{bound}"', cum,
                    )
                cum += v["counts"][-1]
                emit(
                    f"{metric}_bucket", "untyped",
                    f'{label},le="+Inf"', cum,
                )
                emit(f"{metric}_count", "untyped", label, cum)
                # value total (rate(sum)/rate(count) = live mean);
                # older dumps without it render count-only
                if "sum" in v:
                    emit(
                        f"{metric}_sum", "untyped", label, v["sum"]
                    )
    lines: list[str] = []
    for metric in sorted(metrics):
        typ, samples = metrics[metric]
        if typ != "untyped":
            lines.append(f"# TYPE {metric} {typ}")
        for labels, value in samples:
            lines.append(f"{metric}{{{labels}}} {value}")
    return "\n".join(lines) + "\n"


class _Handler(http.server.BaseHTTPRequestHandler):
    def do_GET(self) -> None:  # noqa: N802 (stdlib contract)
        if self.path.split("?")[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        body = render_exposition(self.server.collection).encode()
        self.send_response(200)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args) -> None:
        pass  # scrapes must not spam the daemon's stderr


class Exporter:
    """HTTP /metrics endpoint on a background thread."""

    def __init__(
        self, collection: PerfCountersCollection | None = None
    ) -> None:
        self._collection = (
            collection if collection is not None else _global_collection
        )
        self._server: http.server.ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.addr: tuple[str, int] | None = None

    def start(
        self, host: str = "127.0.0.1", port: int = 0
    ) -> tuple[str, int]:
        srv = http.server.ThreadingHTTPServer((host, port), _Handler)
        srv.collection = self._collection
        self._server = srv
        self.addr = srv.server_address
        self._thread = threading.Thread(
            target=srv.serve_forever, daemon=True
        )
        self._thread.start()
        return self.addr

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
