"""Async ring-buffered logging — the log/Log.cc + common/dout.h analog.

The reference's logger has one property everything else leans on: a
log line is CHEAP unless it is actually flushed. ``dout(N)`` entries
are gathered into an in-memory ring at verbosity up to the subsystem's
*gather* level, but only entries at or below its *log* level go to the
sink — and a crash dumps the most recent ring entries so the verbose
context that was "too expensive to write" is exactly what you get in
the post-mortem (Log::dump_recent, log/Log.cc; the ``dout_subsys``
level pairs of common/dout.h, e.g. ``debug_osd = 1/5``).

Mirrored here:

- ``Logger.dout(prio, msg)``: gathered into a bounded ring when
  ``prio <= gather_level``; queued for the async flusher when
  ``prio <= log_level``. Message objects are formatted lazily — a
  suppressed line never str()s its arguments.
- One background flusher thread per ``Log`` drains the queue to the
  sink (stderr or file), so daemon threads never block on IO
  (Log::entry's queue-swap loop).
- ``dump_recent()`` flushes, then writes the whole gather ring with a
  banner — wired into daemon crash paths and the admin socket
  (``log dump``).
- Per-subsystem ``log_level/gather_level`` pairs adjustable at
  runtime (``log set``), defaulting to the reference's 1/5 stance.
"""

from __future__ import annotations

import collections
import queue
import sys
import threading
import time

DEFAULT_LOG_LEVEL = 1
DEFAULT_GATHER_LEVEL = 5
MAX_RECENT = 10000


class Entry:
    __slots__ = ("stamp", "subsys", "prio", "thread", "parts")

    def __init__(self, subsys: str, prio: int, parts: tuple) -> None:
        self.stamp = time.time()
        self.subsys = subsys
        self.prio = prio
        self.thread = threading.current_thread().name
        self.parts = parts  # formatted lazily at flush/dump time

    def render(self) -> str:
        msg = " ".join(str(p) for p in self.parts)
        ts = time.strftime("%H:%M:%S", time.localtime(self.stamp))
        frac = int((self.stamp % 1) * 1000)
        return (
            f"{ts}.{frac:03d} {self.thread} {self.prio:2d} "
            f"{self.subsys}: {msg}"
        )


class Log:
    """Process logger: gather ring + async flusher (log/Log.cc)."""

    def __init__(
        self,
        sink=None,
        max_recent: int = MAX_RECENT,
    ) -> None:
        self._sink = sink if sink is not None else sys.stderr
        self._levels: dict[str, tuple[int, int]] = {}
        self._recent: collections.deque[Entry] = collections.deque(
            maxlen=max_recent
        )
        self._queue: "queue.Queue[Entry | None]" = queue.Queue()
        self._lock = threading.Lock()
        self._flusher = threading.Thread(
            target=self._flush_loop, name="log-flusher", daemon=True
        )
        self._started = False

    # -- levels --------------------------------------------------------
    def set_level(
        self, subsys: str, log_level: int, gather_level: int | None = None
    ) -> None:
        """``debug_<subsys> = log/gather`` (dout.h level pairs)."""
        if gather_level is None:
            gather_level = max(log_level, DEFAULT_GATHER_LEVEL)
        with self._lock:
            self._levels[subsys] = (log_level, max(log_level, gather_level))

    def levels(self, subsys: str) -> tuple[int, int]:
        with self._lock:
            return self._levels.get(
                subsys, (DEFAULT_LOG_LEVEL, DEFAULT_GATHER_LEVEL)
            )

    def dump_levels(self) -> dict[str, str]:
        with self._lock:
            return {
                s: f"{lo}/{hi}" for s, (lo, hi) in sorted(self._levels.items())
            }

    # -- submission (the dout seam) ------------------------------------
    def submit(self, subsys: str, prio: int, parts: tuple) -> None:
        log_level, gather_level = self.levels(subsys)
        if prio > gather_level:
            return
        entry = Entry(subsys, prio, parts)
        self._recent.append(entry)  # deque append is thread-safe
        if prio <= log_level:
            if not self._started:
                with self._lock:
                    if not self._started:
                        self._flusher.start()
                        self._started = True
            self._queue.put(entry)

    # -- flushing ------------------------------------------------------
    def _write(self, line: str) -> None:
        try:
            self._sink.write(line + "\n")
        except Exception:
            pass  # a broken sink must never take the daemon down

    def _flush_loop(self) -> None:
        while True:
            entry = self._queue.get()
            try:
                if entry is None:
                    return
                self._write(entry.render())
            finally:
                self._queue.task_done()

    def flush(self, timeout: float = 5.0) -> None:
        """Drain queued entries synchronously (Log::flush). Tracks
        in-flight work via task_done, not queue emptiness — an entry
        the flusher has popped but not yet written still counts."""
        if not self._started:
            return
        deadline = time.monotonic() + timeout
        while (
            self._queue.unfinished_tasks and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        try:
            self._sink.flush()
        except Exception:
            pass

    def dump_recent(self, reason: str = "crash") -> list[str]:
        """Write the whole gather ring to the sink with banners and
        return the lines (Log::dump_recent — the crash-context dump).
        """
        self.flush()
        entries = list(self._recent)
        lines = [e.render() for e in entries]
        self._write(f"--- begin dump of recent events ({reason}) ---")
        for line in lines:
            self._write(line)
        self._write(f"--- end dump of recent events ({len(lines)}) ---")
        try:
            self._sink.flush()
        except Exception:
            pass
        return lines

    def set_sink(self, sink) -> None:
        with self._lock:
            self._sink = sink

    def stop(self) -> None:
        if self._started:
            self._queue.put(None)
            self._flusher.join(timeout=2.0)


# Process-global log, like the reference's per-CephContext logger.
root_log = Log()


class Logger:
    """Per-subsystem handle — the ``dout_subsys`` binding."""

    def __init__(self, subsys: str, log: Log | None = None) -> None:
        self.subsys = subsys
        self._log = log if log is not None else root_log

    def dout(self, prio: int, *parts) -> None:
        self._log.submit(self.subsys, prio, parts)

    # Convenience tiers matching common dout conventions: error/info
    # flush by default; debug is ring-gathered only (visible in a
    # crash dump); deep needs raised levels even to gather.
    def error(self, *parts) -> None:
        self._log.submit(self.subsys, -1, parts)

    def info(self, *parts) -> None:
        self._log.submit(self.subsys, 0, parts)

    def debug(self, *parts) -> None:
        self._log.submit(self.subsys, 5, parts)

    def deep(self, *parts) -> None:
        self._log.submit(self.subsys, 10, parts)


def get_logger(subsys: str) -> Logger:
    return Logger(subsys)
