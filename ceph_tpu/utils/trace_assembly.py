"""Cross-daemon trace assembly — span trees, critical paths, Chrome
trace export.

``utils/trace.py`` records flat spans: every span carries a process-
unique ``span_id``, its ``parent_id`` (which crosses the wire on
OSDOp/ECSubWrite/ECSubRead messages), and the end-to-end ``trace_id``
one client op's spans share across the client, the primary, and every
replica.  This module turns a merged pile of span dumps (one process's
``dump_historic_ops``, or several processes' dumps concatenated — the
DCN hosts' admin sockets serve the same format) back into per-trace
span TREES, finds each tree's critical path with per-stage
attribution, and emits:

- a top-N-slowest text report (``format_report``), and
- Chrome trace-event JSON (``chrome_trace``) loadable in Perfetto /
  chrome://tracing, one lane per daemon.

Live ops from ``dump_ops_in_flight`` join as synthetic open-ended
spans (duration = current age), so a trace wedged RIGHT NOW assembles
next to completed ones — the forensics-bundle view of the 167 s
convergence outlier this plane was built to explain.

Interval arithmetic uses the spans' monotonic starts where available
(same process — ``Span.start_mono``) and wall-clock starts otherwise
(cross-process merges), mirroring how the tracer records both.

``tools/trace_tool.py`` is the CLI over this module; the loadgen
driver's ``--trace-capture`` and the soak forensics bundle call
:func:`capture_traces` directly.
"""

from __future__ import annotations

import json


def _end(span: dict) -> float:
    return span["start"] + (span.get("duration") or 0.0)


def _lane(span: dict, inherited: "str | None" = None) -> str:
    """Which daemon's timeline a span belongs on: osd spans tag their
    id; untagged spans ride their parent's lane (an ec_write inside an
    osd_op belongs to that OSD); everything else is the client lane."""
    tags = span.get("tags") or {}
    if "osd" in tags:
        return f"osd.{tags['osd']}"
    if "daemon" in tags:
        return str(tags["daemon"])
    return inherited or "client"


def live_ops_as_spans(ops: "list[dict] | None" = None) -> list[dict]:
    """Convert ``dump_ops_in_flight`` entries into synthetic spans
    (ids outside the tracer's namespace; open-ended duration = age).
    Defaults to the process tracker's current live set."""
    if ops is None:
        from .optracker import op_tracker

        ops = op_tracker.dump_ops_in_flight()["ops"]
    spans = []
    for op in ops:
        spans.append({
            "span_id": f"live-{op['seq']}",
            "parent_id": None,
            "name": f"live:{op['type']}",
            "start": op["started"],
            "start_mono": None,
            "duration": op["age"],
            "tags": {
                "daemon": op["daemon"],
                "live": True,
                "slow": op.get("slow", False),
                "events": [e["event"] for e in op.get("events", [])],
                **{k: v for k, v in op.get("description", {}).items()},
            },
            "trace_id": op.get("trace_id"),
        })
    return spans


def assemble_traces(
    spans: list[dict], live_ops: "list[dict] | None" = None,
) -> list[dict]:
    """Group spans by trace id and rebuild the parent/child trees.

    Returns one dict per trace, sorted by duration (slowest first):

    - ``trace_id``, ``n_spans``, ``start``, ``end``, ``duration``
    - ``roots``: list of nested node dicts (span fields + "children",
      children ordered by start)
    - ``complete``: exactly one root and every non-root span's parent
      resolved — the well-formedness bit the capture contract pins
    - ``orphans``: spans whose parent id is missing from the trace
      (counted; they surface as extra roots)
    """
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        tid = s.get("trace_id")
        if tid is None:
            continue
        by_trace.setdefault(tid, []).append(dict(s))
    if live_ops:
        for s in live_ops_as_spans(live_ops):
            if s.get("trace_id") in by_trace:
                by_trace[s["trace_id"]].append(s)
    trees = []
    for tid, members in by_trace.items():
        ids = {s["span_id"] for s in members}
        nodes = {s["span_id"]: {**s, "children": []} for s in members}
        roots, orphans = [], 0
        for s in members:
            parent = s.get("parent_id")
            node = nodes[s["span_id"]]
            if parent is None:
                roots.append(node)
            elif parent in ids:
                nodes[parent]["children"].append(node)
            else:
                orphans += 1
                roots.append(node)

        def _sort(node: dict) -> None:
            node["children"].sort(
                key=lambda c: (
                    c.get("start_mono")
                    if c.get("start_mono") is not None else c["start"]
                )
            )
            for c in node["children"]:
                _sort(c)

        roots.sort(key=lambda r: r["start"])
        for r in roots:
            _sort(r)
        start = min(s["start"] for s in members)
        end = max(_end(s) for s in members)
        trees.append({
            "trace_id": tid,
            "n_spans": len(members),
            "start": start,
            "end": end,
            "duration": end - start,
            "roots": roots,
            "complete": len(roots) == 1 and orphans == 0,
            "orphans": orphans,
        })
    trees.sort(key=lambda t: -t["duration"])
    return trees


def critical_path(tree: dict) -> dict:
    """The root-to-leaf chain that bounds the trace's wall time, with
    per-stage attribution: each on-path span's SELF time (duration not
    covered by its on-path child) plus explicit gap stages where the
    child starts after the parent ends — the client-queue/wire waits
    between a client op closing and the primary picking it up, or
    between the primary's dispatch and a peer's sub-write."""
    if not tree["roots"]:
        return {"total_s": 0.0, "stages": []}
    node = tree["roots"][0]
    path = [node]
    while node["children"]:
        node = max(node["children"], key=_end)
        path.append(node)
    total = max(_end(n) for n in path) - path[0]["start"]
    stages = []
    lane = None
    for i, n in enumerate(path):
        dur = n.get("duration") or 0.0
        child = path[i + 1] if i + 1 < len(path) else None
        self_t = dur
        if child is not None:
            overlap = max(
                0.0,
                min(_end(n), _end(child))
                - max(n["start"], child["start"]),
            )
            self_t = max(dur - overlap, 0.0)
        lane = _lane(n, lane)
        stages.append({
            "name": n["name"],
            "lane": lane,
            "start": n["start"],
            "self_s": round(self_t, 9),
        })
        if child is not None and child["start"] > _end(n):
            # dead air between parent close and child open: queue
            # wait + wire time, attributable to neither span
            stages.append({
                "name": f"gap:{n['name']}->{child['name']}",
                "lane": "wire/queue",
                "start": _end(n),
                "self_s": round(child["start"] - _end(n), 9),
            })
    return {"total_s": round(total, 9), "stages": stages}


def chrome_trace(trees: list[dict]) -> dict:
    """Chrome trace-event JSON (the Perfetto/chrome://tracing format):
    one complete ("X") event per span, pid 1, one tid lane per daemon,
    thread-name metadata so lanes read osd.N/client."""
    lanes: dict[str, int] = {}
    events: list[dict] = []

    def lane_tid(lane: str) -> int:
        if lane not in lanes:
            lanes[lane] = len(lanes) + 1
        return lanes[lane]

    def emit(node: dict, trace_id: str,
             inherited: "str | None") -> None:
        tags = {
            k: v for k, v in (node.get("tags") or {}).items()
        }
        lane = _lane(node, inherited)
        events.append({
            "name": node["name"],
            "cat": "ceph_tpu",
            "ph": "X",
            "ts": node["start"] * 1e6,
            "dur": (node.get("duration") or 0.0) * 1e6,
            "pid": 1,
            "tid": lane_tid(lane),
            "args": {"trace_id": trace_id, **tags},
        })
        for c in node["children"]:
            emit(c, trace_id, lane)

    for tree in trees:
        for root in tree["roots"]:
            emit(root, tree["trace_id"], None)
    for lane, tid in lanes.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": lane},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _render_node(node: dict, depth: int, out: list[str]) -> None:
    dur = node.get("duration")
    dur_s = f"{dur * 1e3:9.3f} ms" if dur is not None else "      open"
    tags = node.get("tags") or {}
    brief = " ".join(
        f"{k}={tags[k]}" for k in ("op", "oid", "osd", "shard", "tid")
        if k in tags
    )
    out.append(
        f"  {dur_s}  {'  ' * depth}{node['name']}"
        + (f"  [{brief}]" if brief else "")
    )
    for c in node["children"]:
        _render_node(c, depth + 1, out)


def format_report(trees: list[dict], top: int = 10) -> str:
    """Top-N slowest traces as text: the span tree plus the critical
    path's stage attribution."""
    out: list[str] = []
    for i, tree in enumerate(trees[:top]):
        out.append(
            f"== trace {i + 1}/{min(top, len(trees))} "
            f"{tree['trace_id']}  total {tree['duration'] * 1e3:.3f} ms"
            f"  spans {tree['n_spans']}"
            + ("" if tree["complete"]
               else f"  (INCOMPLETE: {len(tree['roots'])} roots, "
                    f"{tree['orphans']} orphans)")
        )
        for root in tree["roots"]:
            _render_node(root, 0, out)
        cp = critical_path(tree)
        out.append(f"  critical path ({cp['total_s'] * 1e3:.3f} ms):")
        for st in cp["stages"]:
            out.append(
                f"    {st['self_s'] * 1e3:9.3f} ms  {st['name']}"
                f"  @{st['lane']}"
            )
    if not trees:
        out.append("(no traces)")
    return "\n".join(out)


def capture_traces(
    limit: int = 8,
    spans: "list[dict] | None" = None,
    live_ops: "list[dict] | None" = None,
) -> dict:
    """Snapshot the process's trace state and assemble the ``limit``
    slowest traces — the loadgen ``--trace-capture`` / forensics-
    bundle entry point.  Everything returned is JSON-serializable."""
    if spans is None:
        from .trace import tracer

        spans = tracer.dump_historic()
    if live_ops is None:
        from .optracker import op_tracker

        live_ops = op_tracker.dump_ops_in_flight()["ops"]
    trees = assemble_traces(spans, live_ops)
    sel = trees[:limit]
    return {
        "captured": len(sel),
        "total_traces": len(trees),
        "trees": sel,
        "critical_paths": [critical_path(t) for t in sel],
        "chrome_json": json.dumps(chrome_trace(sel)),
        "text": format_report(sel, top=limit),
    }
