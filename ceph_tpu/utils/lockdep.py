"""Runtime lock-order and blocking-under-lock detection — the
``common/lockdep.cc`` + ``mutex_debug`` analog.

The cluster tier is a heavily threaded store (~30 named locks across
``cluster/``, ``pipeline/``, ``msg/``, ``store/``, ``loadgen/``), and
the last several rounds each found a concurrency bug by hand that
tooling should have found mechanically: the unlocked daemon-global
req-cache clear, the 2.5 s durability fan-out running *under*
``_op_lock``, stale recovering-marks wedging elections.  This module
is the mechanical net, armed by the ``lockdep`` config option:

- :func:`DebugLock` / :func:`DebugRLock` are drop-in constructors for
  ``threading.Lock()`` / ``threading.RLock()`` carrying a **lock-class
  name** (``"osd.op"``, ``"store.kv"``, ...), an optional **order
  rank**, and an ``op_serializing`` tag.  With ``lockdep=false`` (the
  default) they return the plain threading primitive — the config flag
  is read ONCE, at construction, so the steady-state cost of a
  disarmed build is exactly zero.

- When armed, every (blocking) acquire records the holder thread's
  current held-set into a process-global **lock-dependency graph**
  keyed by lock-class name.  A new edge that closes a cycle in the
  graph is an order inversion — two code paths acquire the same locks
  in opposite orders and WILL deadlock under the right interleaving.
  The cycle is reported (cluster-log ERR, ``lockdep`` perf counters,
  the admin-socket ``lockdep`` dump) with the acquisition backtraces
  of every edge on the cycle, without actually deadlocking: detection
  is observation, the acquire proceeds.

- Locks carrying a **rank** assert the documented order directly:
  acquiring a ranked lock while holding one of greater-or-equal rank
  (different class) is a rank violation even before any reverse path
  exists.  The rank map below documents the cluster tier's intended
  order; unranked locks are covered by cycle detection only.

- :func:`blocking_region` is the blocking-call checkpoint, wired into
  the messenger send path, the dispatcher's device-dispatch wait, the
  peer-RPC drain loop and the sleep shims: entering one while an
  op-serializing lock (``_op_lock``-class, tagged at construction) is
  held flags the site — blocking while holding the op-serializing
  lock IS the single-node tail generator (arxiv 1709.05365's
  queueing/interference finding applied in-process).  Sites that
  serialize *by design* are waived in :data:`BLOCKING_WAIVERS` with
  a one-line justification each; unwaived findings are ERRs.

Rank map (ascending = acquired later / closer to the leaves)::

    10  mon.cmd          monitor command lock (map pushes fan out
                         from under it into the daemons)
    20  osd.op           THE op-serializing lock (client-op order)
    30  osd.pg           daemon PG table + peer addrs
    60  store.*          object-store instance locks
    90  osd.req_flush    documented leaf — never held across another
                         acquire

Everything else is unranked: the graph still catches inversions, but
no order is asserted a priori.  Findings accumulate process-wide;
tests call :func:`reset` for a clean slate and read :func:`dump`
(also served as the admin-socket ``lockdep`` command).
"""

from __future__ import annotations

import sys
import threading

__all__ = [
    "DebugLock",
    "DebugRLock",
    "blocking_region",
    "checked_sleep",
    "enabled",
    "dump",
    "reset",
    "BLOCKING_WAIVERS",
]

#: blocking_region labels that are ALLOWED under an op-serializing
#: lock, each with its one-line justification (the runtime analog of
#: tools/lint_waivers.txt).  A waived hit counts ``blocking_waived``
#: instead of raising an ERR finding — the waiver is a reviewed
#: decision, not a silence switch.
BLOCKING_WAIVERS: dict[str, str] = {
    # The op lock IS the client-op serialization point: the sub-write
    # fan-out and its ack drain are the op itself, bounded by
    # op_timeout (the round-8 fix moved the UNBOUNDED durability
    # fan-out off this lock; the per-op drain stays by design).
    "peers.drain_until":
        "the sub-op drain is the serialized client op itself, "
        "bounded by op_timeout (PR 3 moved the unbounded durability "
        "fan-out off the op lock)",
    # Recovery pushes serialize with live writes UNDER the op lock by
    # construction (round-12 find: a push computed from survivors
    # read at T must not land at T+d over an extent a client write
    # committed in between).
    "recovery.push":
        "catch-up/rewind pushes hold the op lock on purpose — they "
        "must serialize with live writes (the round-12 lost-update "
        "shard tear)",
    # Device dispatches issued from the op path are the op's own
    # encode/decode work — the serialized section IS the operation.
    "dispatcher.submit_wait":
        "the batched device dispatch is the serialized op's own "
        "encode work, not a foreign wait",
    "messenger.send":
        "framed sends are one non-blocking-in-practice socket write "
        "(TCP_NODELAY, k+m-scale fan-out), part of the serialized "
        "op's commit path",
}

# ---------------------------------------------------------------------------
# module state — all guarded by _state_lock, which is a PLAIN lock and
# must never wrap a tracked one (the detector cannot watch itself)
# ---------------------------------------------------------------------------

_state_lock = threading.Lock()
_tls = threading.local()

#: lock-class adjacency: name -> set of names acquired while holding it
_graph: dict[str, set[str]] = {}
#: (holder_name, acquired_name) -> edge record with both backtraces
_edge_info: dict[tuple[str, str], dict] = {}
#: cycle findings (deduped by the frozenset of names on the cycle)
_cycles: list[dict] = []
_cycle_keys: set[frozenset] = set()
#: rank-violation findings, deduped by (held_name, acquired_name)
_rank_violations: list[dict] = []
_rank_keys: set[tuple[str, str]] = set()
#: blocking-under-lock findings, deduped by (label, lock_name)
_blocking: list[dict] = []
_blocking_keys: set[tuple[str, str]] = set()
#: lock classes ever constructed armed (name -> count)
_classes: dict[str, int] = {}

_PERF = None


def _get_perf():
    global _PERF
    if _PERF is None:
        from .perf_counters import PerfCountersBuilder, perf_collection

        _PERF = (
            PerfCountersBuilder(perf_collection, "lockdep")
            .add_u64_counter("locks_constructed",
                             "DebugLocks constructed armed")
            .add_u64_counter("acquires", "tracked blocking acquires")
            .add_u64_counter("edges", "distinct dependency edges recorded")
            .add_u64_counter("cycles", "order-inversion cycles detected")
            .add_u64_counter("rank_violations",
                             "acquires violating the declared rank order")
            .add_u64_counter("blocking_checks",
                             "blocking_region checkpoints crossed")
            .add_u64_counter("blocking_under_lock",
                             "UNWAIVED blocking calls under an "
                             "op-serializing lock")
            .add_u64_counter("blocking_waived",
                             "blocking-under-lock hits on waived labels")
            .create_perf_counters()
        )
    return _PERF


def enabled() -> bool:
    """The construction-time gate: one config read per lock built."""
    from .config import config

    return bool(config.get("lockdep"))


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack(skip: int = 2, limit: int = 20) -> list[tuple[str, int, str]]:
    """A cheap acquisition backtrace: raw (file, line, fn) triples —
    no linecache formatting on the hot path, rendered only when a
    finding is reported."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return []
    out = []
    while f is not None and len(out) < limit:
        co = f.f_code
        out.append((co.co_filename, f.f_lineno, co.co_name))
        f = f.f_back
    return out


def _fmt_stack(frames: list[tuple[str, int, str]]) -> list[str]:
    return [f"{fn}:{ln} in {name}" for fn, ln, name in frames]


def _find_path(src: str, dst: str) -> "list[str] | None":
    """DFS src -> dst over the dependency graph (caller holds
    _state_lock). Returns the node path including both ends."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _graph.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _cluster_log_err(type_: str, message: str, **fields) -> None:
    try:
        from .cluster_log import cluster_log

        cluster_log.log("lockdep", type_, message, severity="ERR",
                        **fields)
    except Exception:
        pass  # reporting must never fault the locked path


class _HeldRecord:
    __slots__ = ("lock", "name", "rank", "op_serializing", "frames")

    def __init__(self, lock, frames) -> None:
        self.lock = lock
        self.name = lock.name
        self.rank = lock.rank
        self.op_serializing = lock.op_serializing
        self.frames = frames


def _record_acquire(lock: "_DebugLockBase",
                    frames: list[tuple[str, int, str]]) -> None:
    """Record the dependency edges held-set -> lock and run the cycle
    + rank checks.  Called BEFORE the blocking acquire so a genuine
    runtime deadlock still leaves its report behind."""
    perf = _get_perf()
    perf.inc("acquires")
    held = _held()
    for h in held:
        if h.name == lock.name:
            continue  # same class (reentry or sibling instance)
        if (
            lock.rank is not None and h.rank is not None
            and h.rank >= lock.rank
            and (h.name, lock.name) not in _rank_keys
        ):
            with _state_lock:
                if (h.name, lock.name) not in _rank_keys:
                    _rank_keys.add((h.name, lock.name))
                    _rank_violations.append({
                        "held": h.name, "held_rank": h.rank,
                        "acquired": lock.name, "acquired_rank": lock.rank,
                        "held_backtrace": _fmt_stack(h.frames),
                        "acquire_backtrace": _fmt_stack(frames),
                    })
                    perf.inc("rank_violations")
                    _cluster_log_err(
                        "lockdep_rank",
                        f"rank violation: {lock.name} "
                        f"(rank {lock.rank}) acquired while holding "
                        f"{h.name} (rank {h.rank})",
                    )
        edge = (h.name, lock.name)
        with _state_lock:
            if edge in _edge_info:
                _edge_info[edge]["count"] += 1
                continue
            _edge_info[edge] = {
                "count": 1,
                "holder_backtrace": _fmt_stack(h.frames),
                "acquire_backtrace": _fmt_stack(frames),
            }
            _graph.setdefault(h.name, set()).add(lock.name)
            perf.inc("edges")
            # the NEW edge h.name -> lock.name closes a cycle iff
            # lock.name already reaches h.name
            path = _find_path(lock.name, h.name)
            if path is None:
                continue
            cycle = path + [lock.name]  # h -> lock implied by closing
            key = frozenset(path)
            if key in _cycle_keys:
                continue
            _cycle_keys.add(key)
            edges = []
            for a, b in zip(cycle[:-1], cycle[1:]):
                info = _edge_info.get((a, b), {})
                edges.append({
                    "from": a, "to": b,
                    "holder_backtrace": info.get("holder_backtrace"),
                    "acquire_backtrace": info.get("acquire_backtrace"),
                })
            finding = {
                "cycle": cycle,
                "pair": [h.name, lock.name],
                "edges": edges,
                # the would-deadlock pair's two acquisition traces:
                # where this thread acquired h then lock, and where
                # some earlier thread did the reverse
                "this_backtrace": _fmt_stack(frames),
                "held_backtrace": _fmt_stack(h.frames),
            }
            _cycles.append(finding)
            perf.inc("cycles")
        if path is not None:
            _cluster_log_err(
                "lockdep_cycle",
                "lock-order inversion: acquiring "
                f"{lock.name} while holding {h.name}, but "
                f"{' -> '.join(path)} already ordered the other way "
                "(would deadlock under the right interleaving)",
            )


class _DebugLockBase:
    """Shared tracking for the Lock/RLock wrappers.  ``name`` is the
    lock CLASS (graph node) — instances of one class share a node, so
    the graph stays readable and sibling instances (per-PG, per-OSD)
    do not explode it."""

    __slots__ = ("_lock", "name", "rank", "op_serializing", "_depth")

    def __init__(self, lock, name: str, rank: "int | None",
                 op_serializing: bool) -> None:
        self._lock = lock
        self.name = name
        self.rank = rank
        self.op_serializing = op_serializing
        self._depth = 0  # RLock reentry (thread-local by ownership)
        with _state_lock:
            _classes[name] = _classes.get(name, 0) + 1
        _get_perf().inc("locks_constructed")

    # -- the threading.Lock surface -------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not blocking:
            # a trylock cannot deadlock: no edge is recorded, the
            # held-set only grows on success
            got = self._lock.acquire(False)
            if got:
                self._note_held(_stack())
            return got
        frames = _stack()
        if self._my_depth() == 0:
            _record_acquire(self, frames)
        got = self._lock.acquire(True, timeout)
        if got:
            self._note_held(frames)
        return got

    def release(self) -> None:
        self._lock.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self:
                del held[i]
                break

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return (f"<{type(self).__name__} {self.name!r} rank={self.rank} "
                f"op_serializing={self.op_serializing} {self._lock!r}>")

    # -- helpers ---------------------------------------------------------
    def _my_depth(self) -> int:
        return sum(1 for h in _held() if h.lock is self)

    def _note_held(self, frames) -> None:
        _held().append(_HeldRecord(self, frames))


class _DebugRLock(_DebugLockBase):
    """Reentrant variant: only the OUTERMOST acquire records edges
    (reentry cannot introduce new order)."""

    def locked(self) -> bool:  # RLock grew .locked() only in 3.12+
        locked = getattr(self._lock, "locked", None)
        return locked() if locked is not None else self._my_depth() > 0

    # threading.Condition integration: delegate the RLock internals so
    # a Condition wrapping a DebugRLock releases ALL recursion levels
    # (and our held-tracking follows).
    def _is_owned(self):
        return self._lock._is_owned()

    def _release_save(self):
        state = self._lock._release_save()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self:
                del held[i]
        return state

    def _acquire_restore(self, state):
        self._lock._acquire_restore(state)
        self._note_held(_stack())


def DebugLock(name: str, rank: "int | None" = None,
              op_serializing: bool = False):
    """``threading.Lock()`` drop-in: a tracked wrapper when the
    ``lockdep`` config option is true AT CONSTRUCTION, else the plain
    primitive (zero steady-state cost)."""
    if not enabled():
        return threading.Lock()
    return _DebugLockBase(threading.Lock(), name, rank, op_serializing)


def DebugRLock(name: str, rank: "int | None" = None,
               op_serializing: bool = False):
    """``threading.RLock()`` drop-in — see :func:`DebugLock`."""
    if not enabled():
        return threading.RLock()
    return _DebugRLock(threading.RLock(), name, rank, op_serializing)


# ---------------------------------------------------------------------------
# blocking-under-lock checkpoints
# ---------------------------------------------------------------------------

class _NullCtx:
    """Shared no-op context — blocking_region sits on hot send/dispatch
    paths, so the disarmed cost must be one call + one thread-local
    read, no generator frame, no allocation."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


def blocking_region(label: str):
    """Checkpoint for code that may block (socket IO, device
    dispatch, sleeps, peer-RPC waits).  Crossing one while an
    op-serializing DebugLock is held records a blocking-under-lock
    finding unless ``label`` is justified in :data:`BLOCKING_WAIVERS`.
    Near-zero cost disarmed: one thread-local read finds no held
    locks."""
    held = getattr(_tls, "held", None)
    if held:
        _check_blocking(label, held)
    return _NULL_CTX


def checked_sleep(seconds: float, label: str = "sleep") -> None:
    """``time.sleep`` shim for polling loops in the threaded tier:
    sleeping while holding an op-serializing lock parks every queued
    client op behind a timer — exactly the tail generator lockdep
    exists to catch."""
    import time

    with blocking_region(label):
        time.sleep(seconds)


def _check_blocking(label: str, held: list) -> None:
    op_locks = [h for h in held if h.op_serializing]
    perf = _get_perf()
    perf.inc("blocking_checks")
    if not op_locks:
        return
    h = op_locks[-1]
    waived = label in BLOCKING_WAIVERS
    if waived:
        perf.inc("blocking_waived")
        return
    key = (label, h.name)
    if key in _blocking_keys:
        perf.inc("blocking_under_lock")
        return
    with _state_lock:
        if key in _blocking_keys:
            return
        _blocking_keys.add(key)
        _blocking.append({
            "label": label,
            "lock": h.name,
            "lock_backtrace": _fmt_stack(h.frames),
            "blocking_backtrace": _fmt_stack(_stack(skip=3)),
        })
    perf.inc("blocking_under_lock")
    _cluster_log_err(
        "lockdep_blocking",
        f"blocking region {label!r} entered while holding "
        f"op-serializing lock {h.name} (unwaived — fix the site or "
        "justify it in lockdep.BLOCKING_WAIVERS)",
    )


# ---------------------------------------------------------------------------
# reporting surface
# ---------------------------------------------------------------------------

def dump() -> dict:
    """The admin-socket ``lockdep`` command payload: the dependency
    graph summary and every finding, with backtraces."""
    with _state_lock:
        return {
            "enabled": enabled(),
            "lock_classes": dict(_classes),
            "edges": {
                f"{a} -> {b}": info["count"]
                for (a, b), info in sorted(_edge_info.items())
            },
            "cycles": [dict(c) for c in _cycles],
            "rank_violations": [dict(r) for r in _rank_violations],
            "blocking_under_lock": [dict(b) for b in _blocking],
            "blocking_waivers": dict(BLOCKING_WAIVERS),
        }


def findings() -> dict:
    """Just the failure counts — the soak/bench green-check surface."""
    with _state_lock:
        return {
            "cycles": len(_cycles),
            "rank_violations": len(_rank_violations),
            "blocking_under_lock": len(_blocking),
        }


def reset() -> None:
    """Clear the graph and every finding (tests / soak laps). Held
    sets of live threads are untouched — they reflect reality."""
    with _state_lock:
        _graph.clear()
        _edge_info.clear()
        _cycles.clear()
        _cycle_keys.clear()
        _rank_violations.clear()
        _rank_keys.clear()
        _blocking.clear()
        _blocking_keys.clear()
        _classes.clear()
    if _PERF is not None:
        _PERF.reset()
