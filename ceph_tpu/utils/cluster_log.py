"""Cluster-wide structured event log — the ``ceph.log`` analog.

The reference aggregates health-relevant events from every daemon into
one monitor-held log (``mon/LogMonitor.cc``, the ``ceph log last``
surface): OSD down/up marks, slow-op complaints, scrub errors, peering
stalls.  Per-daemon ``dout`` rings (utils/log.py) answer "what was
THIS daemon doing"; this module answers "what happened to the
CLUSTER" — the first file a red soak run is triaged from.

Here the daemons share one process, so the aggregation point is a
process-global bounded ring of structured events.  Each event carries:

- ``ts``        wall-clock stamp (merging across DCN host processes
                aligns on wall time)
- ``daemon``    the reporting daemon ("mon", "osd.3", ...)
- ``type``      a stable event-type slug ("osd_down", "slow_op",
                "scrub_error", "peering_stalled", "net_fault_armed",
                "crash_point", ...)
- ``severity``  DBG < INF < WRN < ERR
- ``message``   human-readable one-liner
- ``epoch``     osdmap epoch when the reporter knows it
- ``trace_id``  the CURRENT trace id when the event fired inside a
                span — a slow-op complaint links straight to the op's
                assembled trace (tools/trace_tool.py)
- extra keyword fields, JSON-serializable

Query via ``cluster_log.last(n)`` or the admin socket's ``log last``
(the ``ceph log last N`` analog); ``cli health`` summarizes recent
warnings.  An optional JSONL sink (``cluster_log_file`` config, or
``set_sink``) persists events for the soak forensics bundle.

Event counts ride the ``cluster_log`` perf-counter set (``events``,
``events_warn``, ``events_error``) — on ``perf dump`` and the
Prometheus exporter like every other set.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

SEVERITIES = ("DBG", "INF", "WRN", "ERR")

#: ring capacity — the reference keeps a few thousand ceph.log lines
#: in the mon store; a soak forensics tail wants hours of churn
MAX_EVENTS = 8192


#: ONE process-wide counter set shared by every ClusterLog instance
#: (tests build private rings; their events still count here instead
#: of re-registering and orphaning the global set)
_PERF = None


def _get_perf():
    global _PERF
    if _PERF is None:
        from .perf_counters import PerfCountersBuilder, perf_collection

        _PERF = (
            PerfCountersBuilder(perf_collection, "cluster_log")
            .add_u64_counter("events", "cluster-log events recorded")
            .add_u64_counter("events_warn", "events at WRN severity")
            .add_u64_counter("events_error", "events at ERR severity")
            .create_perf_counters()
        )
    return _PERF


class ClusterLog:
    """Process-global structured event ring (+ optional JSONL sink)."""

    def __init__(self, max_events: int = MAX_EVENTS) -> None:
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=max_events)
        self._sink = None
        self._sink_path: str | None = None
        #: True when the open sink came from the ``cluster_log_file``
        #: config (only then may a config change replace/close it —
        #: an explicit set_sink always wins)
        self._sink_from_cfg = False

    # -- sink management -----------------------------------------------
    def set_sink(self, path: "str | None") -> None:
        """Point the JSONL sink at ``path`` (None closes it)."""
        with self._lock:
            self._set_sink_locked(path)
            self._sink_from_cfg = False

    def _set_sink_locked(self, path: "str | None") -> None:
        if self._sink is not None:
            try:
                self._sink.close()
            except Exception:
                pass
            self._sink = None
        self._sink_path = path or None
        if path:
            try:
                self._sink = open(path, "a", encoding="utf-8")
            except OSError:
                self._sink = None  # a bad sink must not kill logging

    def _maybe_refresh_sink(self) -> None:
        """Honor ``cluster_log_file`` lazily (checked per event under
        the lock; the config get is a handful of dict lookups).  Only
        ever replaces a sink the config itself opened."""
        from .config import config

        want = config.get("cluster_log_file") or None
        if want is not None and want != self._sink_path:
            self._set_sink_locked(want)
            self._sink_from_cfg = True
        elif (
            want is None and self._sink_from_cfg
            and self._sink_path is not None
        ):
            self._set_sink_locked(None)
            self._sink_from_cfg = False

    # -- submission -----------------------------------------------------
    def log(
        self,
        daemon: str,
        type: str,
        message: str,
        severity: str = "INF",
        epoch: "int | None" = None,
        trace_id: "str | None" = None,
        **fields,
    ) -> dict:
        """Record one cluster event.  ``trace_id`` defaults to the
        calling thread's current span's trace id, so events fired from
        inside the pipeline correlate with the op's assembled trace."""
        if severity not in SEVERITIES:
            severity = "INF"
        if trace_id is None:
            from .trace import tracer

            trace_id = tracer.current()[0]
        event = {
            "ts": time.time(),
            "daemon": str(daemon),
            "type": str(type),
            "severity": severity,
            "message": str(message),
            "epoch": epoch,
            "trace_id": trace_id,
        }
        if fields:
            event.update(fields)
        line = None
        with self._lock:
            self._ring.append(event)
            self._maybe_refresh_sink()
            if self._sink is not None:
                try:
                    line = json.dumps(event, default=str)
                    self._sink.write(line + "\n")
                    self._sink.flush()
                except Exception:
                    pass  # the ring is the source of truth
        perf = _get_perf()
        perf.inc("events")
        if severity == "WRN":
            perf.inc("events_warn")
        elif severity == "ERR":
            perf.inc("events_error")
        return event

    # -- query ----------------------------------------------------------
    def last(
        self, n: int = 20, daemon: "str | None" = None,
        severity: "str | None" = None,
    ) -> list[dict]:
        """The newest ``n`` events, oldest first (``ceph log last``).
        ``severity`` filters at-or-above ("WRN" = WRN + ERR)."""
        with self._lock:
            events = list(self._ring)
        if daemon is not None:
            events = [e for e in events if e["daemon"] == daemon]
        if severity is not None:
            floor = SEVERITIES.index(severity)
            events = [
                e for e in events
                if SEVERITIES.index(e["severity"]) >= floor
            ]
        return events[-int(n):] if n else events

    def summary(self) -> dict:
        """Counts + the most recent warnings — the ``cli health``
        digest."""
        with self._lock:
            events = list(self._ring)
        warn = [e for e in events if e["severity"] in ("WRN", "ERR")]
        return {
            "events": len(events),
            "warnings": len(warn),
            "recent_warnings": warn[-8:],
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


#: the process cluster log, like the reference's mon-held ceph.log
cluster_log = ClusterLog()
