"""Runtime utilities: platform selection, perf counters, config,
tracing — the ``src/common/`` analog layer."""

from .platform import honor_platform_env

__all__ = ["honor_platform_env"]
