"""Runtime utilities: platform selection, perf counters, config,
tracing — the ``src/common/`` analog layer."""

from .platform import honor_platform_env
from .perf_counters import (
    PerfCounters,
    PerfCountersBuilder,
    PerfCountersCollection,
    perf_collection,
)
from .config import ConfigProxy, Option, config
from .trace import Tracer, tracer
from .admin_socket import AdminSocket, admin_socket

__all__ = [
    "honor_platform_env",
    "PerfCounters",
    "PerfCountersBuilder",
    "PerfCountersCollection",
    "perf_collection",
    "ConfigProxy",
    "Option",
    "config",
    "Tracer",
    "tracer",
    "AdminSocket",
    "admin_socket",
]
