"""Runtime utilities: platform selection, perf counters, config,
tracing — the ``src/common/`` analog layer."""

from .platform import (
    apply_debug_modes,
    honor_platform_env,
    install_debug_observer,
)
from .perf_counters import (
    PerfCounters,
    PerfCountersBuilder,
    PerfCountersCollection,
    perf_collection,
)
from .config import ConfigProxy, Option, config
from .trace import Tracer, tracer
from .optracker import NULL_OP, OpTracker, TrackedOp, op_tracker
from .cluster_log import ClusterLog, cluster_log
from .admin_socket import AdminSocket, admin_socket

__all__ = [
    "apply_debug_modes",
    "honor_platform_env",
    "install_debug_observer",
    "PerfCounters",
    "PerfCountersBuilder",
    "PerfCountersCollection",
    "perf_collection",
    "ConfigProxy",
    "Option",
    "config",
    "Tracer",
    "tracer",
    "NULL_OP",
    "OpTracker",
    "TrackedOp",
    "op_tracker",
    "ClusterLog",
    "cluster_log",
    "AdminSocket",
    "admin_socket",
]
