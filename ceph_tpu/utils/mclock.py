"""mClock QoS scheduler — the dmclock analog (osd/scheduler/
mClockScheduler.{h,cc} + the vendored src/dmclock library).

The reference arbitrates OSD work between client IO, recovery,
backfill and scrub with the mClock algorithm (Gulati et al., OSDI'10):
each class gets a **reservation** (minimum service rate it is
guaranteed), a **weight** (share of spare capacity) and a **limit**
(service-rate cap), all in COST UNITS per second — cost is
byte-proportional at the call sites (cluster/qos.py), so a 4 MB push
advances a clock ~65x further than a 4 KB stat.  Every request is
tagged on arrival relative to its class's previous request (mClock
paper, Algorithm 1):

    R_i = max(now, R_{i-1} + cost/reservation)   (guarantee clock)
    P_i = max(now, P_{i-1} + cost/weight)        (proportional clock)
    L_i = max(now, L_{i-1} + cost/limit)         (cap clock)

and dequeue runs two phases:

1. **constraint-based**: any head whose R tag has matured runs first
   (smallest R) — reservations are met before everything else;
2. **weight-based**: otherwise the smallest P tag among heads whose L
   tag has matured — spare capacity splits by weight, capped by
   limits. The chosen class's queued R tags shift back by one
   reservation quantum (the paper's adjustment so weight-phase service
   doesn't also consume the reservation).

Classes are DYNAMIC (the dmclock client-registry role): tenant-tagged
client ops enqueue as ``client.<tenant>`` and untagged ops as
``client.<pool>``; a dotted class with no profile of its own inherits
its prefix's profile (``client.gold`` -> the ``client`` row) until a
per-tenant QoS spec (stored in pool metadata, pushed with the osdmap)
registers one.  ``set_profiles`` swaps the whole profile table LIVE:
existing queues re-bind to the new rates immediately — already-issued
tags stand, the next enqueue advances from them at the new rate (how
the reference applies ``osd_mclock_profile`` changes without a
scheduler rebuild).

A class that goes idle and returns gets its clocks re-anchored at
``now`` (the idle-client adjustment): no banked credit, no penalty.

Observability: every class counts reservation-phase and weight-phase
dequeues, limit-throttle stalls and served cost; ``dump()`` returns
the live per-class tags, depths and tag-lag (the admin-socket
``dump_mclock`` surface).

Pure and clock-injected: deterministic under test, wall-clock in the
daemon.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass


@dataclass(frozen=True)
class ClientProfile:
    """QoS knobs for one class (osd_mclock_scheduler_*_{res,wgt,lim})."""

    reservation: float = 0.0  # cost units/sec guaranteed (0 = none)
    weight: float = 1.0       # share of spare capacity
    limit: float = 0.0        # cost units/sec cap (0 = unlimited)


#: the reference's balanced-profile shape (osd_mclock_profile=balanced:
#: client vs background recovery/backfill/scrub allocations)
BALANCED_PROFILE = {
    "client": ClientProfile(reservation=50.0, weight=2.0, limit=0.0),
    "recovery": ClientProfile(reservation=25.0, weight=1.0, limit=100.0),
    "backfill": ClientProfile(reservation=10.0, weight=0.5, limit=100.0),
    "scrub": ClientProfile(reservation=0.0, weight=0.2, limit=50.0),
    "gc": ClientProfile(reservation=0.0, weight=0.2, limit=50.0),
}


class _Entry:
    __slots__ = ("item", "cost", "r", "p", "l")

    def __init__(self, item, cost, r, p, l) -> None:
        self.item = item
        self.cost = cost
        self.r = r
        self.p = p
        self.l = l


class _ClassQueue:
    __slots__ = (
        "profile", "q", "prev_r", "prev_p", "prev_l", "last_seen",
        "enqueued", "dequeued_r", "dequeued_p", "throttled",
        "served_cost",
    )

    def __init__(self, profile: ClientProfile) -> None:
        self.profile = profile
        self.q: deque[_Entry] = deque()
        self.prev_r = 0.0
        self.prev_p = 0.0
        self.prev_l = 0.0
        self.last_seen = -math.inf
        # lifetime service accounting (the qos perf set reads these)
        self.enqueued = 0
        self.dequeued_r = 0
        self.dequeued_p = 0
        self.throttled = 0
        self.served_cost = 0.0


class MClockScheduler:
    """Single-server mClock over named, dynamically created classes."""

    def __init__(
        self,
        profiles: dict[str, ClientProfile] | None = None,
        clock=time.monotonic,
        idle_age: float = 1.0,
    ) -> None:
        self.profiles = dict(profiles or BALANCED_PROFILE)
        self.clock = clock
        self.idle_age = idle_age
        self._classes: dict[str, _ClassQueue] = {}

    def _profile_for(self, name: str) -> ClientProfile:
        """Resolve a class name to its profile: exact row, else the
        dotted prefix's row (``client.gold`` -> ``client``) — how an
        unregistered tenant inherits the pool-wide client QoS."""
        prof = self.profiles.get(name)
        if prof is not None:
            return prof
        if "." in name:
            prof = self.profiles.get(name.split(".", 1)[0])
            if prof is not None:
                return prof
        return ClientProfile()

    def _class(self, name: str) -> _ClassQueue:
        cq = self._classes.get(name)
        if cq is None:
            cq = _ClassQueue(self._profile_for(name))
            self._classes[name] = cq
        return cq

    def set_profiles(
        self, profiles: dict[str, ClientProfile]
    ) -> None:
        """Swap the profile table live (QoS spec push / slosh-knob
        turn): every existing class re-resolves against the new table.
        Issued tags stand; the next enqueue advances at the new rate."""
        self.profiles = dict(profiles)
        for name, cq in self._classes.items():
            cq.profile = self._profile_for(name)

    def set_profile(self, name: str, profile: ClientProfile) -> None:
        """Register/replace one class's profile live (a per-tenant QoS
        spec landing from the map push)."""
        self.profiles[name] = profile
        for cls, cq in self._classes.items():
            cq.profile = self._profile_for(cls)

    def __len__(self) -> int:
        return sum(len(c.q) for c in self._classes.values())

    # -- enqueue: per-request tags (Algorithm 1) ------------------------
    def enqueue(self, class_name: str, item, cost: float = 1.0) -> None:
        now = self.clock()
        cq = self._class(class_name)
        p = cq.profile
        if not cq.q and now - cq.last_seen > self.idle_age:
            # idle-client adjustment: re-anchor, no banked credit
            cq.prev_r = cq.prev_p = cq.prev_l = now
            # first request after idle is immediately eligible
            r = now if p.reservation > 0 else math.inf
            pt = now
            lt = now
        else:
            r = (
                max(now, cq.prev_r + cost / p.reservation)
                if p.reservation > 0 else math.inf
            )
            pt = max(now, cq.prev_p + cost / max(p.weight, 1e-9))
            lt = (
                max(now, cq.prev_l + cost / p.limit)
                if p.limit > 0 else now
            )
        cq.prev_r = r if r != math.inf else cq.prev_r
        cq.prev_p = pt
        cq.prev_l = lt
        cq.last_seen = now
        cq.enqueued += 1
        cq.q.append(_Entry(item, cost, r, pt, lt))

    # -- dequeue: two-phase pick ---------------------------------------
    def dequeue(self) -> tuple[str, object] | None:
        """Pop the next runnable (class, item); None when the queue is
        empty or every class is limit-gated right now."""
        now = self.clock()
        heads = [
            (name, cq) for name, cq in self._classes.items() if cq.q
        ]
        if not heads:
            return None
        # phase 1: constraint-based (matured reservations, smallest R)
        ready = [
            (cq.q[0].r, name, cq) for name, cq in heads
            if cq.q[0].r <= now
        ]
        if ready:
            _, name, cq = min(ready)
            entry = cq.q.popleft()
            cq.last_seen = now
            cq.dequeued_r += 1
            cq.served_cost += entry.cost
            return (name, entry.item)
        # phase 2: weight-based among classes under their limit
        eligible = [
            (cq.q[0].p, name, cq) for name, cq in heads
            if cq.q[0].l <= now
        ]
        if eligible:
            _, name, cq = min(eligible)
            entry = cq.q.popleft()
            # weight-phase service must not also consume reservation
            # credit: shift the class's queued R tags one quantum back
            if cq.profile.reservation > 0:
                delta = entry.cost / cq.profile.reservation
                for e in cq.q:
                    e.r -= delta
                cq.prev_r -= delta
            cq.last_seen = now
            cq.dequeued_p += 1
            cq.served_cost += entry.cost
            return (name, entry.item)
        # every queued class is limit-gated: a throttle stall
        for _name, cq in heads:
            cq.throttled += 1
        return None

    def next_ready(self) -> float | None:
        """Earliest time a dequeue could succeed (for worker sleeps)."""
        times = []
        for cq in self._classes.values():
            if cq.q:
                times.append(min(cq.q[0].r, cq.q[0].l))
        return min(times) if times else None

    # -- introspection (the dump_mclock surface) ------------------------
    def dump(self) -> dict:
        """Live per-class state: profile rates, queue depth, head
        tags, tag-lag (head R or L tag minus now — how far behind or
        ahead of its clocks the class is), and the lifetime service
        counters.  Classes with no queue and no history are elided."""
        now = self.clock()
        out: dict[str, dict] = {}
        for name, cq in sorted(self._classes.items()):
            head = cq.q[0] if cq.q else None
            tag_lag = 0.0
            if head is not None:
                gate = head.r if head.r != math.inf else head.l
                tag_lag = max(gate - now, 0.0)
            out[name] = {
                "profile": {
                    "reservation": cq.profile.reservation,
                    "weight": cq.profile.weight,
                    "limit": cq.profile.limit,
                },
                "depth": len(cq.q),
                "head_tags": None if head is None else {
                    "r": None if head.r == math.inf else head.r,
                    "p": head.p,
                    "l": head.l,
                    "cost": head.cost,
                },
                "tag_lag_s": tag_lag,
                "enqueued": cq.enqueued,
                "dequeued_r": cq.dequeued_r,
                "dequeued_p": cq.dequeued_p,
                "throttled": cq.throttled,
                "served_cost": cq.served_cost,
            }
        return out
