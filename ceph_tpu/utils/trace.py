"""Span tracing + historic-op ring — the ZTracer/OpTracker analog.

The reference threads ``ZTracer::Trace`` handles through the EC
pipeline signatures (osd/ECBackend.h:70-94) and keeps an in-memory
history of completed ops served as ``dump_historic_ops``
(common/TrackedOp). Here: a context-manager ``span`` records name,
parent, wall duration, and tags into a bounded ring; nesting is
tracked per-thread so pipeline code never passes handles explicitly.

On TPU the same spans also emit ``jax.profiler.TraceAnnotation``
blocks when profiling is active, so host-side pipeline stages line up
with device timelines in XLA profile captures.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Span:
    span_id: int
    parent_id: int | None
    name: str
    start: float
    duration: float | None = None
    tags: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "duration": self.duration,
            "tags": self.tags,
        }


class Tracer:
    def __init__(self, history: int = 512, enabled: bool = True) -> None:
        self.enabled = enabled
        self._ids = itertools.count(1)
        self._history: deque[Span] = deque(maxlen=history)
        self._lock = threading.Lock()
        self._tls = threading.local()

    def _stack(self) -> list[Span]:
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        return self._tls.stack

    @contextmanager
    def span(self, name: str, **tags):
        if not self.enabled:
            yield None
            return
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        sp = Span(next(self._ids), parent, name, time.time(), tags=tags)
        stack.append(sp)
        t0 = time.perf_counter()
        annotation = None
        try:
            import jax.profiler

            annotation = jax.profiler.TraceAnnotation(name)
            annotation.__enter__()
        except Exception:
            annotation = None
        try:
            yield sp
        finally:
            if annotation is not None:
                annotation.__exit__(None, None, None)
            sp.duration = time.perf_counter() - t0
            stack.pop()
            with self._lock:
                self._history.append(sp)

    def dump_historic(self, limit: int | None = None) -> list[dict]:
        with self._lock:
            spans = list(self._history)
        if limit is not None:
            spans = spans[-limit:]
        return [s.as_dict() for s in spans]

    def clear(self) -> None:
        with self._lock:
            self._history.clear()


# Process-global tracer.
tracer = Tracer()
