"""Span tracing + historic-op ring — the ZTracer/OpTracker analog.

The reference threads ``ZTracer::Trace`` handles through the EC
pipeline signatures (osd/ECBackend.h:70-94) and keeps an in-memory
history of completed ops served as ``dump_historic_ops``
(common/TrackedOp). Here: a context-manager ``span`` records name,
parent, wall duration, and tags into a bounded ring; nesting is
tracked per-thread so pipeline code never passes handles explicitly.

On TPU the same spans also emit ``jax.profiler.TraceAnnotation``
blocks when profiling is active, so host-side pipeline stages line up
with device timelines in XLA profile captures.
"""

from __future__ import annotations

import itertools
import os
import secrets
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

#: process-unique prefix so trace ids stay distinct across the DCN
#: tier's OS-process hosts (the blkin trace-id role)
_TRACE_PREFIX = f"{os.getpid():x}-{secrets.token_hex(2)}"

#: jax.profiler.TraceAnnotation, resolved ONCE on first span instead
#: of an import+try/except per span (the round-14 hot-path fix: the
#: per-span import dominated small-op span cost). Lazy rather than
#: import-time so ``import ceph_tpu`` stays jax-free for the
#: multichip dryrun (the admin-socket builtin-registration contract).
#: Sentinel False = unresolved; None = resolved-absent.
_ANNOTATION_CLS: "object" = False


def _annotation_cls():
    global _ANNOTATION_CLS
    if _ANNOTATION_CLS is False:
        try:
            import jax.profiler

            _ANNOTATION_CLS = jax.profiler.TraceAnnotation
        except Exception:
            _ANNOTATION_CLS = None
    return _ANNOTATION_CLS


@dataclass
class Span:
    #: globally unique (process-prefixed) — parent links survive
    #: merging dump_historic output across DCN host processes, where
    #: bare per-process counters would collide
    span_id: str
    parent_id: str | None
    name: str
    start: float
    duration: float | None = None
    tags: dict = field(default_factory=dict)
    #: one id per END-TO-END operation, carried across the wire
    #: (client op -> primary -> replica sub-ops all share it)
    trace_id: str | None = None
    #: monotonic clock at span open, taken at the SAME instant as the
    #: wall-clock ``start``: trace assembly orders spans and computes
    #: intervals on (start_mono, start_mono + duration) within a
    #: process — mixing wall starts with perf_counter durations made
    #: cross-thread ordering wobble by the wall clock's granularity
    start_mono: float | None = None

    def as_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "start_mono": self.start_mono,
            "duration": self.duration,
            "tags": self.tags,
            "trace_id": self.trace_id,
        }


class Tracer:
    def __init__(self, history: int = 512, enabled: bool = True) -> None:
        self.enabled = enabled
        self._ids = itertools.count(1)
        self._history: deque[Span] = deque(maxlen=history)
        self._lock = threading.Lock()
        self._tls = threading.local()

    def _stack(self) -> list[Span]:
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        return self._tls.stack

    @contextmanager
    def span(self, name: str, **tags):
        if not self.enabled:
            yield None
            return
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        trace_id = (
            stack[-1].trace_id
            if stack
            else f"{_TRACE_PREFIX}-{next(self._ids)}"
        )
        t0 = time.perf_counter()
        sp = Span(
            f"{_TRACE_PREFIX}-{next(self._ids)}", parent, name,
            time.time(), tags=tags, trace_id=trace_id, start_mono=t0,
        )
        stack.append(sp)
        annotation = None
        cls = _annotation_cls()
        if cls is not None:
            try:
                annotation = cls(name)
                annotation.__enter__()
            except Exception:
                annotation = None
        try:
            yield sp
        finally:
            if annotation is not None:
                annotation.__exit__(None, None, None)
            sp.duration = time.perf_counter() - t0
            stack.pop()
            with self._lock:
                self._history.append(sp)

    def current(self) -> tuple[str | None, str | None]:
        """(trace_id, span_id) of the innermost open span — what a
        sender stamps into an outgoing message."""
        stack = self._stack()
        if not stack:
            return None, None
        return stack[-1].trace_id, stack[-1].span_id

    @contextmanager
    def continue_trace(self, trace_id: str | None, parent_id: str | None):
        """Adopt a REMOTE trace context (the wire hop of
        ZTracer/blkin: the reference threads trace handles through the
        EC pipeline signatures and the sub-op messages,
        osd/ECBackend.h:70-94). Spans opened inside link to the
        sender's span and share its trace id, so one client op's
        spans correlate across the client, the primary, and every
        replica — dump_historic filtered by trace_id IS the
        distributed trace."""
        if not self.enabled or trace_id is None:
            yield
            return
        stack = self._stack()
        marker = Span(
            parent_id if parent_id is not None else "",
            None, "<remote>", time.time(), trace_id=trace_id,
        )
        stack.append(marker)
        try:
            yield
        finally:
            stack.pop()

    def dump_historic(self, limit: int | None = None) -> list[dict]:
        with self._lock:
            spans = list(self._history)
        if limit is not None:
            spans = spans[-limit:]
        return [s.as_dict() for s in spans]

    def clear(self) -> None:
        with self._lock:
            self._history.clear()


# Process-global tracer.
tracer = Tracer()
