"""Backend selection helpers.

Environments that tunnel JAX to remote accelerators (the axon site
hook) set the ``jax_platforms`` *config* key at interpreter start,
which silently outranks the ``JAX_PLATFORMS`` env var. Tools that are
explicitly asked for a platform (unit tests, the driver's virtual-mesh
dry run, CPU benches) call :func:`honor_platform_env` before first
device use so the config agrees with the env.
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Make jax_platforms config match an explicit JAX_PLATFORMS=cpu.

    No-op when the env var is unset or requests non-CPU platforms —
    the default (tunnel/TPU) path stays untouched.
    """
    want = [p.strip() for p in os.environ.get("JAX_PLATFORMS", "").split(",") if p.strip()]
    if want == ["cpu"]:
        import jax

        jax.config.update("jax_platforms", "cpu")
