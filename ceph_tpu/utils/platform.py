"""Backend selection helpers.

Environments that tunnel JAX to remote accelerators (the axon site
hook) set the ``jax_platforms`` *config* key at interpreter start,
which silently outranks the ``JAX_PLATFORMS`` env var. Tools that are
explicitly asked for a platform (unit tests, the driver's virtual-mesh
dry run, CPU benches) call :func:`honor_platform_env` before first
device use so the config agrees with the env.
"""

from __future__ import annotations

import os


def trace_state_clean() -> bool:
    """True when no jax trace is active — the guard for caching device
    arrays (a tracer cached from inside jit poisons every later call).
    jax 0.9 moved trace_state_clean out of the public jax.core; try
    both homes and fail CLOSED (treat unknown as tracing)."""
    for modname in ("jax.core", "jax._src.core"):
        try:
            import importlib

            mod = importlib.import_module(modname)
            fn = getattr(mod, "trace_state_clean", None)
            if fn is not None:
                return bool(fn())
        except Exception:
            continue
    return False


def apply_debug_modes() -> None:
    """Map the debug_* config options onto JAX debug flags — the
    runtime analog of the reference's WITH_ASAN/WITH_TSAN compile-time
    sanitizer toggles (CMakeLists.txt:673-690; SURVEY.md §5.2). Safe
    to call any time; also installed as a config observer so
    ``config set debug_nan_check true`` takes effect live."""
    import jax

    from ceph_tpu.utils.config import config

    jax.config.update("jax_debug_nans", config.get("debug_nan_check"))
    jax.config.update("jax_disable_jit", config.get("debug_disable_jit"))


def install_debug_observer() -> None:
    """Re-apply debug modes whenever a debug_* option changes."""
    from ceph_tpu.utils.config import config

    config.add_observer("debug_", lambda _name, _value: apply_debug_modes())


def honor_platform_env() -> None:
    """Make jax_platforms config match an explicit JAX_PLATFORMS=cpu.

    No-op when the env var is unset or requests non-CPU platforms —
    the default (tunnel/TPU) path stays untouched.
    """
    want = [p.strip() for p in os.environ.get("JAX_PLATFORMS", "").split(",") if p.strip()]
    if want == ["cpu"]:
        import jax

        jax.config.update("jax_platforms", "cpu")
