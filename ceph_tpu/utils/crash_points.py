"""Crash-point fault injection — named yield points inside critical
transitions (peering AND the RMW commit path), armable by tests.

Grown out of ``cluster/peering.py`` (round 12) where the registry
covered only peering transitions; it now lives in the neutral utils
layer so the RMW pipeline (``pipeline/rmw.py``) and the OSD daemon's
sub-write apply/ack/commit hops can fire points without a
pipeline→cluster import inversion. The spirit is loadgen's
op-offset fault hooks applied to INTERLEAVINGS: a test arms a point
to pause (and later release), fail the transition, kill the firing
daemon, or run a callback — turning 1-in-20 thread races into pinned,
repeatable regression tests.

Named points (the registry itself is name-agnostic):

- ``peering.<state>.<point>`` / ``catchup.*`` — peering transitions
  (see cluster/peering.py's state diagram).
- ``rmw.prepare_done`` — primary: write planned, encoded, journaled;
  no sub-write dispatched yet.
- ``rmw.subwrite_applied_before_ack`` — receiving OSD: the sub-write
  txn is durable in its store, the ack not yet on the wire.
- ``rmw.primary_before_commit`` — primary: the LAST sub-write ack
  arrived, the op not yet marked committed.
- ``rmw.primary_committed_before_reply`` — primary: the op committed
  (client callback fired), the OSDOpReply not yet sent.

A ``kill`` at each of those four is one mid-commit crash class; the
kill-at-point → restart → replay tier pins that pglog rollback/
rollforward converges and committed reads return committed bytes.
"""

from __future__ import annotations

import threading


class CrashPointAbort(Exception):
    """Raised at an armed crash point to unwind the transition (the
    ``fail`` and ``kill`` actions); peering parks in ``incomplete``
    and retries from the tick, an RMW hop unwinds like the crash it
    models (the connection/op dies, recovery converges it)."""


class ArmedPoint:
    """One armed crash point. ``pause`` blocks the firing thread at
    the point until :meth:`release` (tests synchronize on
    :meth:`wait_hit`); ``fail`` raises :class:`CrashPointAbort`;
    ``kill`` hard-stops the firing daemon (on a side thread — stop()
    joins threads the point may be on) and then aborts the
    transition; a callable runs with the fire context."""

    def __init__(self, name, action, osd=None, pool=None, pgid=None,
                 count=1, pause_cap=30.0) -> None:
        if action not in ("pause", "fail", "kill") and not callable(action):
            raise ValueError(f"unknown crash action {action!r}")
        self.name = name
        self.action = action
        self.osd = osd
        self.pool = pool
        self.pgid = pgid
        self.remaining = count  # None = unlimited until cleared
        self.pause_cap = pause_cap
        self.hits = 0
        self._hit = threading.Event()
        self._released = threading.Event()

    def matches(self, name, daemon, pg) -> bool:
        if name != self.name:
            return False
        if self.osd is not None and (
            daemon is None or daemon.osd_id != self.osd
        ):
            return False
        if self.pool is not None and (
            pg is None or pg.pool != self.pool
        ):
            return False
        if self.pgid is not None and (
            pg is None or pg.pgid != self.pgid
        ):
            return False
        return True

    def wait_hit(self, timeout: float = 10.0) -> bool:
        return self._hit.wait(timeout)

    def release(self) -> None:
        self._released.set()

    def _fire(self, daemon, pg, ctx) -> None:
        self.hits += 1
        self._hit.set()
        try:
            # chaos runs read the cluster log to line injected faults
            # up against their fallout; lazy import (leaf module)
            from .cluster_log import cluster_log

            cluster_log.log(
                f"osd.{daemon.osd_id}" if daemon is not None else "proc",
                "crash_point",
                f"{self.name} fired "
                f"({self.action if isinstance(self.action, str) else 'callable'})",
                severity="WRN",
            )
        except Exception:
            pass  # observability must never change the injected fault
        if self.action == "pause":
            # capped: an un-released point must not wedge the FSM
            # forever if a test dies before release()
            self._released.wait(self.pause_cap)
            return
        if self.action == "fail":
            raise CrashPointAbort(self.name)
        if self.action == "kill":
            if daemon is not None:
                # a crash silences the node ATOMICALLY: close the data
                # plane synchronously (no reply/ack framed after the
                # crash point may escape — an RMW kill must lose the
                # client reply like the crash it models, not win a
                # race against the stop thread), then stop the daemon
                # on a side thread (stop() joins threads this very
                # point may be firing on)
                for attr in ("messenger", "peers"):
                    try:
                        getattr(daemon, attr).shutdown()
                    except Exception:
                        pass
                threading.Thread(
                    target=daemon.stop, daemon=True,
                    name=f"crash-kill-osd.{daemon.osd_id}",
                ).start()
            raise CrashPointAbort(self.name)
        self.action(daemon=daemon, pg=pg, **ctx)


class CrashPointRegistry:
    """Process-global registry of named yield points. ``fire()`` is a
    single attribute check when nothing is armed — the
    instrumentation costs nothing in production."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._armed: list[ArmedPoint] = []

    def arm(
        self, name: str, action="pause", *, osd=None, pool=None,
        pgid=None, count=1, pause_cap: float = 30.0,
    ) -> ArmedPoint:
        pt = ArmedPoint(
            name, action, osd=osd, pool=pool, pgid=pgid, count=count,
            pause_cap=pause_cap,
        )
        with self._lock:
            self._armed.append(pt)
        return pt

    def clear(self) -> None:
        with self._lock:
            for pt in self._armed:
                pt.release()  # free any thread parked at a pause
            self._armed.clear()

    def fire(self, name: str, daemon=None, pg=None, **ctx) -> None:
        if not self._armed:  # the hot-path fast exit
            return
        with self._lock:
            pt = next(
                (p for p in self._armed if p.matches(name, daemon, pg)),
                None,
            )
            if pt is None:
                return
            if pt.remaining is not None:
                pt.remaining -= 1
                if pt.remaining <= 0:
                    self._armed.remove(pt)
        pt._fire(daemon, pg, ctx)  # outside the lock: it may block


#: the process-global crash-point registry tests arm
crash_points = CrashPointRegistry()
