"""Typed performance counters — the ``PerfCounters`` analog.

Mirrors common/perf_counters.{h,cc}: a builder declares typed metrics
(u64 counters, gauges, time totals, averages with count+sum, histogram
buckets), instances update them cheaply at runtime, and a process
collection serves ``perf dump``-style JSON through the admin socket
(common/admin_socket.cc) — the same schema shape the reference's
``ceph daemon ... perf dump`` emits: averages as {avgcount, sum},
histograms as bucket arrays.

Thread-safe via one lock per counter set (the reference uses atomics;
Python increments are cheap enough under a lock here).
"""

from __future__ import annotations

import bisect
import enum
import threading


class CounterType(enum.Enum):
    U64 = "u64"            # monotonically increasing counter
    GAUGE = "gauge"        # settable value
    TIME = "time"          # accumulated seconds
    AVG = "avg"            # count + sum (time or value averages)
    HISTOGRAM = "histogram"


class PerfCounters:
    """One subsystem's counter set; create via PerfCountersBuilder."""

    def __init__(self, name: str, schema: dict[str, dict]) -> None:
        self.name = name
        self._schema = schema
        self._lock = threading.Lock()
        self._values: dict[str, object] = {}
        #: histogram value totals (Prometheus histograms carry a
        #: ``_sum`` so rate(sum)/rate(count) gives a live mean)
        self._hist_sums: dict[str, float] = {}
        for key, spec in schema.items():
            if spec["type"] is CounterType.AVG:
                self._values[key] = [0, 0.0]  # avgcount, sum
            elif spec["type"] is CounterType.HISTOGRAM:
                self._values[key] = [0] * (len(spec["buckets"]) + 1)
                self._hist_sums[key] = 0.0
            else:
                self._values[key] = 0 if spec["type"] in (
                    CounterType.U64, CounterType.GAUGE
                ) else 0.0

    def _check(self, key: str, *types: CounterType) -> dict:
        spec = self._schema.get(key)
        if spec is None:
            raise KeyError(f"{self.name}: no counter {key!r}")
        if types and spec["type"] not in types:
            raise TypeError(
                f"{self.name}.{key} is {spec['type'].value}, not "
                f"{'/'.join(t.value for t in types)}"
            )
        return spec

    def inc(self, key: str, by: int = 1) -> None:
        self._check(key, CounterType.U64)
        with self._lock:
            self._values[key] += by

    def set(self, key: str, value) -> None:
        self._check(key, CounterType.GAUGE)
        with self._lock:
            self._values[key] = value

    def tinc(self, key: str, seconds: float) -> None:
        self._check(key, CounterType.TIME)
        with self._lock:
            self._values[key] += seconds

    def ainc(self, key: str, value: float) -> None:
        """Add one sample to an average (count += 1, sum += value)."""
        self._check(key, CounterType.AVG)
        with self._lock:
            pair = self._values[key]
            pair[0] += 1
            pair[1] += value

    def hinc(self, key: str, value: float) -> None:
        spec = self._check(key, CounterType.HISTOGRAM)
        with self._lock:
            self._values[key][bisect.bisect_right(spec["buckets"], value)] += 1
            self._hist_sums[key] += value

    def get(self, key: str):
        with self._lock:
            v = self._values[key]
            return list(v) if isinstance(v, list) else v

    def reset(self) -> None:
        """Zero every counter, gauge, time accumulator, average pair
        and histogram bucket (the ``perf reset`` admin command): bench
        A/B legs and soak iterations start from clean counters instead
        of differencing against a snapshot."""
        with self._lock:
            for key, spec in self._schema.items():
                if spec["type"] is CounterType.AVG:
                    self._values[key] = [0, 0.0]
                elif spec["type"] is CounterType.HISTOGRAM:
                    self._values[key] = [0] * (len(spec["buckets"]) + 1)
                    self._hist_sums[key] = 0.0
                elif spec["type"] in (CounterType.U64, CounterType.GAUGE):
                    self._values[key] = 0
                else:
                    self._values[key] = 0.0

    def dump(self) -> dict:
        out: dict[str, object] = {}
        with self._lock:
            for key, spec in self._schema.items():
                v = self._values[key]
                if spec["type"] is CounterType.AVG:
                    out[key] = {"avgcount": v[0], "sum": v[1]}
                elif spec["type"] is CounterType.HISTOGRAM:
                    out[key] = {
                        "buckets": list(spec["buckets"]),
                        "counts": list(v),
                        "sum": self._hist_sums[key],
                    }
                else:
                    out[key] = v
        return out


class PerfCountersBuilder:
    """Declare a counter set, then ``create_perf_counters()``
    (PerfCountersBuilder, common/perf_counters.h)."""

    def __init__(self, collection: "PerfCountersCollection", name: str) -> None:
        self._collection = collection
        self._name = name
        self._schema: dict[str, dict] = {}

    def _add(self, key: str, type: CounterType, desc: str, **extra):
        if key in self._schema:
            raise ValueError(f"duplicate counter {key!r}")
        self._schema[key] = {"type": type, "desc": desc, **extra}
        return self

    def add_u64_counter(self, key: str, desc: str = ""):
        return self._add(key, CounterType.U64, desc)

    def add_u64_gauge(self, key: str, desc: str = ""):
        return self._add(key, CounterType.GAUGE, desc)

    def add_time(self, key: str, desc: str = ""):
        return self._add(key, CounterType.TIME, desc)

    def add_avg(self, key: str, desc: str = ""):
        return self._add(key, CounterType.AVG, desc)

    def add_histogram(self, key: str, buckets: list[float], desc: str = ""):
        if sorted(buckets) != list(buckets):
            raise ValueError("histogram buckets must be sorted")
        return self._add(key, CounterType.HISTOGRAM, desc, buckets=buckets)

    def create_perf_counters(self) -> PerfCounters:
        pc = PerfCounters(self._name, dict(self._schema))
        self._collection.register(pc)
        return pc


class PerfCountersCollection:
    """All counter sets in the process (PerfCountersCollectionImpl)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sets: dict[str, PerfCounters] = {}

    def register(self, pc: PerfCounters) -> None:
        with self._lock:
            # Same-name re-registration replaces (a rebuilt pipeline
            # supersedes its predecessor's counters).
            self._sets[pc.name] = pc

    def deregister(self, name: str) -> None:
        with self._lock:
            self._sets.pop(name, None)

    def reset(self, name: str | None = None) -> int:
        """Zero one named set, or every registered set (``perf
        reset`` over the admin socket). Returns how many sets were
        reset; an unknown name raises KeyError like the other admin
        lookups."""
        with self._lock:
            if name is None:
                targets = list(self._sets.values())
            else:
                pc = self._sets.get(name)
                if pc is None:
                    raise KeyError(f"no counter set {name!r}")
                targets = [pc]
        for pc in targets:
            pc.reset()
        return len(targets)

    def dump(self) -> dict:
        with self._lock:
            return {name: pc.dump() for name, pc in sorted(self._sets.items())}

    def snapshot(self) -> dict[str, tuple[dict, dict]]:
        """name -> (schema, dumped values), sorted — the exporter
        surface (schema carries each counter's type and histogram
        bucket bounds)."""
        with self._lock:
            sets = sorted(self._sets.items())
        return {name: (dict(pc._schema), pc.dump()) for name, pc in sets}


# Process-global collection, served by the admin socket's "perf dump".
perf_collection = PerfCountersCollection()
