"""Live-operation tracking — the ``common/TrackedOp`` + ``OpTracker``
analog.

``utils/trace.py`` records spans only AFTER they complete: a wedged or
minute-long op contributes nothing to ``dump_historic_ops`` until it
is over — exactly when an operator most needs to see it.  This module
is the live half of the observability plane: every in-flight operation
(objecter client op, primary RMW op, peer sub-op RPC, peering pass,
recovery push, backfill item) registers a :class:`TrackedOp` whose
typed ``mark_event`` checkpoints build an event timeline while the op
runs.  The admin socket's ``dump_ops_in_flight`` returns the live set
age-sorted (oldest — the interesting one — first), each op with its
timeline, exactly the surface ``ceph daemon osd.N dump_ops_in_flight``
serves from TrackedOp::dump.

A watchdog thread (started lazily with the first tracked op) flags
ops older than ``osd_op_complaint_time``:

- the owning daemon's ``<daemon>.optracker`` counter set bumps
  ``slow_ops_total`` and the ``slow_ops`` gauge (currently-slow live
  ops), and a ``slow_op_age_s`` log2 histogram records final ages of
  slow ops as they complete — all on ``perf dump`` and the Prometheus
  exporter like every other set;
- a WRN ``slow_op`` complaint lands in the cluster log
  (utils/cluster_log.py), carrying the op's trace id so the complaint
  links straight to the assembled trace.

Cost discipline: with ``osd_enable_op_tracker=false`` every
``register`` returns the shared :data:`NULL_OP`, whose ``mark_event``
is a no-op — the bench cluster phase's tracked-vs-untracked A/B leg
(``trace_overhead_frac``) pins the enabled plane's cost.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager

#: slow-op age histogram bounds, seconds (log2: 1 ms .. ~35 min)
AGE_BUCKETS_S = [0.001 * (1 << i) for i in range(22)]


def _daemon_key(daemon: str) -> str:
    """Collapse pipeline-grade names ("osd.3.pool.2.rmw") to the
    owning daemon ("osd.3") so per-daemon counter sets don't multiply
    per PG; anything else passes through."""
    parts = str(daemon).split(".")
    if parts[0] == "osd" and len(parts) > 1 and parts[1].isdigit():
        return f"osd.{parts[1]}"
    return daemon or "proc"


class TrackedOp:
    """One live operation: identity, event timeline, age."""

    __slots__ = (
        "seq", "op_type", "daemon", "desc", "trace_id", "start",
        "start_mono", "events", "slow", "_tracker",
    )

    def __init__(
        self, tracker: "OpTracker", seq: int, op_type: str,
        daemon: str, trace_id: "str | None", desc: dict,
    ) -> None:
        self._tracker = tracker
        self.seq = seq
        self.op_type = op_type
        self.daemon = daemon
        self.trace_id = trace_id
        self.desc = desc
        self.start = time.time()
        self.start_mono = time.monotonic()
        #: (monotonic stamp, event string) — appends are GIL-atomic,
        #: dumps snapshot via list()
        self.events: list[tuple[float, str]] = []
        self.slow = False

    # -- the checkpoint surface (TrackedOp::mark_event) -----------------
    def mark_event(self, event: str, **detail) -> None:
        if detail:
            event = event + " " + " ".join(
                f"{k}={v}" for k, v in sorted(detail.items())
            )
        self.events.append((time.monotonic(), event))

    def age(self) -> float:
        return time.monotonic() - self.start_mono

    def finish(self, event: "str | None" = None) -> None:
        if event is not None:
            self.mark_event(event)
        self._tracker._unregister(self)

    def __enter__(self) -> "TrackedOp":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.mark_event(f"error:{exc_type.__name__}")
        self.finish()

    def as_dict(self) -> dict:
        t0 = self.start_mono
        return {
            "seq": self.seq,
            "type": self.op_type,
            "daemon": self.daemon,
            "description": dict(self.desc),
            "trace_id": self.trace_id,
            "started": self.start,
            "age": round(self.age(), 6),
            "slow": self.slow,
            "events": [
                {"t": round(t - t0, 6), "event": ev}
                for t, ev in list(self.events)
            ],
        }


class _NullOp:
    """The tracker-off handle: every surface a no-op so call sites
    never branch on the config themselves."""

    __slots__ = ()
    slow = False
    trace_id = None

    def mark_event(self, event: str, **detail) -> None:
        pass

    def age(self) -> float:
        return 0.0

    def finish(self, event: "str | None" = None) -> None:
        pass

    def __enter__(self) -> "_NullOp":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass

    def as_dict(self) -> dict:
        return {}


NULL_OP = _NullOp()


class OpTracker:
    """Process-global registry of live ops + the slow-op watchdog."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._live: dict[int, TrackedOp] = {}
        self._perf: dict[str, object] = {}
        self._watchdog: threading.Thread | None = None
        self._wake = threading.Event()

    # -- registration ---------------------------------------------------
    def enabled(self) -> bool:
        from .config import config

        return bool(config.get("osd_enable_op_tracker"))

    def register(
        self, op_type: str, daemon: str = "", trace_id: "str | None" = None,
        **desc,
    ) -> "TrackedOp | _NullOp":
        """Track one live op.  ``trace_id`` defaults to the calling
        thread's current span's trace id (the wire-carried one), so
        live ops and completed spans assemble into the same trees."""
        if not self.enabled():
            return NULL_OP
        if trace_id is None:
            from .trace import tracer

            trace_id = tracer.current()[0]
        top = TrackedOp(
            self, next(self._seq), op_type, _daemon_key(daemon),
            trace_id, desc,
        )
        pc = self._perf_for(top.daemon)
        with self._lock:
            self._live[top.seq] = top
            if self._watchdog is None:
                self._watchdog = threading.Thread(
                    target=self._watchdog_loop, daemon=True,
                    name="optracker-watchdog",
                )
                self._watchdog.start()
        pc.inc("ops_tracked")
        return top

    @contextmanager
    def track(
        self, op_type: str, daemon: str = "",
        trace_id: "str | None" = None, **desc,
    ):
        """Register-for-a-scope: finishes on exit, marking
        ``error:<type>`` first when the scope raised."""
        top = self.register(op_type, daemon, trace_id, **desc)
        try:
            yield top
        except BaseException as e:
            top.mark_event(f"error:{type(e).__name__}")
            raise
        finally:
            top.finish()

    def _unregister(self, top: TrackedOp) -> None:
        with self._lock:
            if self._live.pop(top.seq, None) is None:
                return  # double-finish: idempotent
        if top.slow:
            # final age of a slow op, for the complaint histogram
            self._perf_for(top.daemon).hinc("slow_op_age_s", top.age())

    # -- per-daemon counters --------------------------------------------
    def _perf_for(self, daemon: str):
        with self._lock:
            pc = self._perf.get(daemon)
        if pc is not None:
            return pc
        from .perf_counters import PerfCountersBuilder, perf_collection

        pc = (
            PerfCountersBuilder(perf_collection, f"{daemon}.optracker")
            .add_u64_counter("ops_tracked", "ops ever registered")
            .add_u64_gauge("slow_ops", "live ops currently past "
                                       "osd_op_complaint_time")
            .add_u64_counter("slow_ops_total",
                             "ops that ever crossed the complaint age")
            .add_histogram(
                "slow_op_age_s", AGE_BUCKETS_S,
                "final ages of completed slow ops (seconds, log2)",
            )
            .create_perf_counters()
        )
        with self._lock:
            # racing creators: keep the first registered instance
            pc = self._perf.setdefault(daemon, pc)
        return pc

    # -- the watchdog ---------------------------------------------------
    def _watchdog_loop(self) -> None:
        from .config import config

        while True:
            complaint = float(config.get("osd_op_complaint_time"))
            self._wake.wait(max(0.02, min(complaint / 4.0, 0.5)))
            self._wake.clear()
            try:
                self._sweep(complaint)
            except Exception:
                pass  # the watchdog must outlive any counter fault

    def _sweep(self, complaint: float) -> None:
        with self._lock:
            ops = list(self._live.values())
        slow_by_daemon: dict[str, int] = {}
        for top in ops:
            if top.age() < complaint:
                continue
            slow_by_daemon[top.daemon] = (
                slow_by_daemon.get(top.daemon, 0) + 1
            )
            if not top.slow:
                top.slow = True
                self._perf_for(top.daemon).inc("slow_ops_total")
                last = top.events[-1][1] if top.events else "<no events>"
                from .cluster_log import cluster_log

                cluster_log.log(
                    top.daemon, "slow_op",
                    f"{top.op_type} blocked for {top.age():.2f}s "
                    f"(currently: {last}; {top.desc})",
                    severity="WRN", trace_id=top.trace_id,
                    op_seq=top.seq,
                )
        with self._lock:
            perfs = dict(self._perf)
        for daemon, pc in perfs.items():
            pc.set("slow_ops", slow_by_daemon.get(daemon, 0))

    def poke(self) -> None:
        """Wake the watchdog now (tests shorten the complaint clock)."""
        self._wake.set()

    # -- the dump surface (dump_ops_in_flight) --------------------------
    def dump_ops_in_flight(self, daemon: "str | None" = None) -> dict:
        with self._lock:
            ops = list(self._live.values())
        if daemon is not None:
            key = _daemon_key(daemon)
            ops = [t for t in ops if t.daemon == key]
        ops.sort(key=lambda t: t.start_mono)  # oldest first
        return {"num_ops": len(ops), "ops": [t.as_dict() for t in ops]}

    def live_count(self) -> int:
        with self._lock:
            return len(self._live)

    def finish_all(
        self, daemon: "str | None" = None, event: str = "abandoned"
    ) -> int:
        """Finish every live op (optionally one daemon's) with a
        terminal mark — daemon teardown: a stopped daemon's parked ops
        died with it and must not complain forever."""
        key = _daemon_key(daemon) if daemon is not None else None
        with self._lock:
            tops = [
                t for t in self._live.values()
                if key is None or t.daemon == key
            ]
        for t in tops:
            t.finish(event)
        return len(tops)

    def clear(self) -> None:
        """Drop every live op (test isolation; production never)."""
        with self._lock:
            self._live.clear()


#: the process OpTracker, served by ``dump_ops_in_flight``
op_tracker = OpTracker()
