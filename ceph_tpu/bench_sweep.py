"""k/m sweep benchmark harness — the bench.sh + bench.html analog.

Mirrors qa/workunits/erasure-code/bench.sh:20-48: sweep k (and m)
across plugins, run the encode and/or decode workload for each
configuration through the ``ecbench`` CLI machinery, and emit results
as JSON lines plus an optional self-contained HTML bar chart (the
flot-plot role, dependency-free).

    python -m ceph_tpu.bench_sweep --plugins isa jerasure \
        --k 2 4 8 --m 2 4 --size 16777216 --iterations 5 \
        --html bench.html

``--baseline`` ignores the sweep axes and reproduces the five
BASELINE.md benchmark configs 1:1 (jerasure rs k=4 m=2 4K; isa rs
k=8 m=3 64K; cauchy k=10 m=4 1M x 1024 stripes; CLAY (8,4,d=11)
single-chunk repair; CRC32C over 4/16/64 KiB blocks):

    python -m ceph_tpu.bench_sweep --baseline
"""

from __future__ import annotations

import argparse
import json
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="ceph_tpu.bench_sweep")
    p.add_argument("--plugins", nargs="+", default=["isa", "jerasure"])
    p.add_argument("--k", nargs="+", type=int, default=[2, 4, 6, 8, 11])
    p.add_argument("--m", nargs="+", type=int, default=[2])
    p.add_argument("--workloads", nargs="+", default=["encode", "decode"],
                   choices=["encode", "decode"])
    p.add_argument("--size", type=int, default=16 * 1024 * 1024)
    p.add_argument("--iterations", type=int, default=10)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--erasures", type=int, default=1)
    p.add_argument("--html", default=None,
                   help="also write a self-contained HTML chart here")
    p.add_argument("--baseline", action="store_true",
                   help="run the five BASELINE.md configs instead of "
                        "the k/m sweep")
    return p.parse_args(argv)


# BASELINE.md "Benchmark configs to reproduce 1:1". Sizes follow the
# config text (per-chunk/stripe bytes); iterations kept modest so the
# full set runs in minutes on one chip.
BASELINE_CONFIGS: list[tuple[str, list[str]]] = [
    # --size is total bytes per iteration across the stripe batch:
    # chunk_bytes * k * batch.
    ("1 jerasure reed_sol_van k=4 m=2 4K chunks",
     ["encode", "--plugin", "jerasure", "-P", "technique=reed_sol_van",
      "-P", "k=4", "-P", "m=2", "--size", str(4096 * 4 * 256),
      "--batch", "256", "--iterations", "20"]),
    ("2 isa rs k=8 m=3 64K stripe",
     ["encode", "--plugin", "isa", "-P", "k=8", "-P", "m=3",
      "--size", str(64 * 1024 * 64), "--batch", "64",
      "--iterations", "20"]),
    ("3 cauchy k=10 m=4 1M objects, 1024-stripe batch",
     ["encode", "--plugin", "jerasure", "-P", "technique=cauchy_good",
      "-P", "k=10", "-P", "m=4", "--size", str((1 << 20) * 1024),
      "--batch", "1024", "--iterations", "10"]),
    ("4 clay (8,4,d=11) single-chunk repair",
     ["repair", "--plugin", "clay", "-P", "k=8", "-P", "m=4",
      "-P", "d=11", "--size", str(1 << 20), "--iterations", "12"]),
    ("5a crc32c 4K blocks", ["checksum", "--csum-alg", "crc32c",
     "--csum-block", "4096", "--size", str(64 << 20), "--iterations", "5"]),
    ("5b crc32c 16K blocks", ["checksum", "--csum-alg", "crc32c",
     "--csum-block", "16384", "--size", str(64 << 20), "--iterations", "5"]),
    ("5c crc32c 64K blocks", ["checksum", "--csum-alg", "crc32c",
     "--csum-block", "65536", "--size", str(64 << 20), "--iterations", "5"]),
]


def run_baseline() -> list[dict]:
    from ceph_tpu import bench_cli

    results = []
    for name, argv in BASELINE_CONFIGS:
        try:
            elapsed, total_kib = bench_cli.run(bench_cli.parse_args(argv))
        except (ValueError, RuntimeError) as e:
            row = {"config": name, "error": str(e)}
        else:
            gbps = total_kib * 1024 / max(elapsed, 1e-9) / 1e9
            row = {
                "config": name,
                "seconds": round(elapsed, 6),
                "KiB": int(total_kib),
                "GBps": round(gbps, 3),
            }
        results.append(row)
        print(json.dumps(row), flush=True)
    return results


def sweep(args) -> list[dict]:
    from ceph_tpu import bench_cli

    results = []
    for plugin in args.plugins:
        for k in args.k:
            for m in args.m:
                for workload in args.workloads:
                    argv = [
                        workload, "--plugin", plugin,
                        "-P", f"k={k}", "-P", f"m={m}",
                        "--size", str(args.size),
                        "--iterations", str(args.iterations),
                        "--batch", str(args.batch),
                        "--erasures", str(args.erasures),
                    ]
                    if plugin == "jerasure":
                        argv += ["-P", "technique=reed_sol_van"]
                    try:
                        elapsed, total_kib = bench_cli.run(
                            bench_cli.parse_args(argv)
                        )
                    except (ValueError, RuntimeError) as e:
                        results.append({
                            "plugin": plugin, "k": k, "m": m,
                            "workload": workload, "error": str(e),
                        })
                        continue
                    gbps = total_kib * 1024 / max(elapsed, 1e-9) / 1e9
                    row = {
                        "plugin": plugin, "k": k, "m": m,
                        "workload": workload,
                        "seconds": round(elapsed, 6),
                        "KiB": int(total_kib),
                        "GBps": round(gbps, 3),
                    }
                    results.append(row)
                    print(json.dumps(row), flush=True)
    return results


_HTML = """<!doctype html><meta charset="utf-8">
<title>ceph_tpu EC bench sweep</title>
<style>
 body {{ font: 14px system-ui; margin: 2em; }}
 .bar {{ height: 18px; background: #4a79a4; margin: 2px 0; }}
 .row {{ display: grid; grid-template-columns: 22em 1fr 7em;
         gap: .75em; align-items: center; }}
 .lbl {{ text-align: right; color: #333; }}
 .val {{ color: #555; }}
</style>
<h1>EC throughput sweep</h1>
<div id="chart"></div>
<script>
const data = {data};
const max = Math.max(...data.filter(d => d.GBps).map(d => d.GBps));
const el = document.getElementById("chart");
for (const d of data) {{
  const row = document.createElement("div");
  row.className = "row";
  const label = d.config ??
    `${{d.plugin}} k=${{d.k}} m=${{d.m}} ${{d.workload}}`;
  if (d.error) {{
    row.innerHTML = `<div class="lbl">${{label}}</div>` +
      `<div></div><div class="val">error</div>`;
  }} else {{
    const w = (100 * d.GBps / max).toFixed(1);
    row.innerHTML = `<div class="lbl">${{label}}</div>` +
      `<div><div class="bar" style="width:${{w}}%"></div></div>` +
      `<div class="val">${{d.GBps}} GB/s</div>`;
  }}
  el.appendChild(row);
}}
</script>
"""


def write_html(path: str, results: list[dict]) -> None:
    with open(path, "w") as f:
        f.write(_HTML.format(data=json.dumps(results)))


def main(argv=None) -> int:
    args = parse_args(argv)
    results = run_baseline() if args.baseline else sweep(args)
    if args.html:
        write_html(args.html, results)
        print(f"wrote {args.html}", file=sys.stderr)
    return 0 if all("error" not in r for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
