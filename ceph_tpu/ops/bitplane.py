"""Bit-plane GF(2^8) engine: erasure-code math as mod-2 MXU matmuls.

The reference's hot loop is ``ec_encode_data`` / ``jerasure_matrix_encode``
(SIMD GF multiply-accumulate over byte lanes, isa/ErasureCodeIsa.cc:268).
TPUs have no pshufb-style byte table lookup, so we lower differently
(SURVEY.md section 7): a GF(2^8) generator matrix G[m, k] becomes one
binary matrix B[m*8, k*8] (each entry an 8x8 multiply-by-constant GF(2)
block), data bytes become 8 bit-planes, and

    parity_bits[m*8, N] = (B @ data_bits[k*8, N]) mod 2

which the MXU executes as an int8 matmul with int32 accumulation (exact:
the products are 0/1, so any contraction we build fits easily), followed
by ``& 1`` and bit re-packing. The same engine runs decode (B = cached
inverted submatrix rows), parity delta (B = single generator column),
and the Liberation-family native bit-matrix codes (packet layout
instead of byte bit-planes).

Engine invariant (round 6, shared with the Pallas kernels in
pallas_encode.py): **stripes live on batch/lane axes, never in the
contraction**. The einsum below batches stripes on the leading axes
with the bare [R*8, S*8] matrix — zero structural waste — and the
kernel path now does the same (stripes on the grid and lane axes; the
round-3..5 kernels block-diagonaled two stripes into the contraction,
clocking 2x the MACs with half of them zeros).

All functions are shape-polymorphic over leading batch axes and jit/vmap
friendly (static shapes, no data-dependent control flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _accum_dtypes() -> tuple[jnp.dtype, jnp.dtype]:
    """(operand dtype, accumulator dtype) for the mod-2 matmul.

    int8 x int8 -> int32 rides the MXU at full integer throughput on TPU
    and is exact for our contraction sizes (<= 256 ones per row).
    """
    return jnp.int8, jnp.int32


def unpack_bits(x: jax.Array) -> jax.Array:
    """[..., S, N] uint8 -> [..., S*8, N] bits in {0,1} (LSB-first planes).

    Row s*8+b of the output is bit b of shard s, matching the LSB-first
    bit convention of ``ceph_tpu.gf.tables.mul_bitmatrix``.
    """
    shifts = jnp.arange(8, dtype=jnp.uint8)
    b = (x[..., :, None, :] >> shifts[:, None]) & jnp.uint8(1)
    return b.reshape(*x.shape[:-2], x.shape[-2] * 8, x.shape[-1])


def pack_bits(bits: jax.Array) -> jax.Array:
    """[..., S*8, N] bits in {0,1} -> [..., S, N] uint8 (LSB-first)."""
    s8, n = bits.shape[-2], bits.shape[-1]
    assert s8 % 8 == 0, s8
    b = bits.reshape(*bits.shape[:-2], s8 // 8, 8, n).astype(jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    return jnp.sum(b << shifts[:, None], axis=-2, dtype=jnp.uint8)


def mod2_matmul(bmat: jax.Array, bits: jax.Array) -> jax.Array:
    """(bmat @ bits) mod 2. bmat [R, C] in {0,1}; bits [..., C, N] in {0,1}.

    Integer matmul with int32 accumulation, then parity of the count.
    Deterministic regardless of reduction order (bit-compatibility
    requirement — SURVEY.md section 7 "Hard parts").
    """
    op_dtype, acc_dtype = _accum_dtypes()
    # Keep N in the minor (lane) dimension end-to-end: out[..., R, N] with
    # bmat as LHS. The transposed formulation ([N, R] + relayout) measured
    # 3500x slower on v5e; this form lets XLA fuse unpack -> int8 MXU
    # matmul -> mod-2 -> pack into one kernel at HBM speed.
    acc = jnp.einsum(
        "rc,...cn->...rn",
        bmat.astype(op_dtype),
        bits.astype(op_dtype),
        preferred_element_type=acc_dtype,
    )
    return (acc & 1).astype(jnp.uint8)


def gf_encode_bitplane(bitmatrix: jax.Array, data: jax.Array) -> jax.Array:
    """Apply a GF(2^8) code in bit-plane form.

    ``bitmatrix``: [R*8, S*8] binary (from gf.gf_matrix_to_bitmatrix of an
    [R, S] GF matrix). ``data``: [..., S, N] uint8 shards. Returns
    [..., R, N] uint8 — parity shards for encode, reconstructed shards for
    decode, delta contributions for apply_delta.
    """
    return pack_bits(mod2_matmul(bitmatrix, unpack_bits(data)))


def xor_bytes(a: jax.Array, b: jax.Array) -> jax.Array:
    """GF(2^8) addition — used by encode_delta (new XOR old, per
    ErasureCodeInterface.h:471 parity-delta contract)."""
    return jnp.bitwise_xor(a, b)


def unpack_bits_lanes(x: jax.Array) -> jax.Array:
    """[..., C, P] uint8 -> [..., C, P*8] bits, bit planes along lanes.

    Element [..., c, p*8+b] is bit b of byte [..., c, p] (LSB-first).
    """
    shifts = jnp.arange(8, dtype=jnp.uint8)
    b = (x[..., :, :, None] >> shifts) & jnp.uint8(1)
    return b.reshape(*x.shape[:-1], x.shape[-1] * 8)


def pack_bits_lanes(bits: jax.Array) -> jax.Array:
    """Inverse of unpack_bits_lanes: [..., C, P*8] -> [..., C, P] uint8."""
    p8 = bits.shape[-1]
    assert p8 % 8 == 0, p8
    b = bits.reshape(*bits.shape[:-1], p8 // 8, 8).astype(jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    return jnp.sum(b << shifts, axis=-1, dtype=jnp.uint8)


def packet_mod2_apply(bitmatrix: jax.Array, packets: jax.Array) -> jax.Array:
    """Native bit-matrix codes on the jerasure *packet* layout.

    ``packets``: [..., C, P] uint8 where each of the C = k*w rows is a
    packet of P bytes (chunk = w consecutive packets). Output row r is the
    XOR of packets selected by bitmatrix row r — bytewise XOR. Unpacking
    byte bits along the lane axis keeps the selection a single [R, C]
    mod-2 matmul (XOR acts independently per bit lane).
    """
    bits = unpack_bits_lanes(packets)  # [..., C, P*8]
    return pack_bits_lanes(mod2_matmul(bitmatrix, bits))


def gf_mul_const_bytes(c: int, x: jax.Array) -> jax.Array:
    """Multiply every byte by GF constant ``c`` (device path).

    Used by apply_delta for single-coefficient parity updates; lowered via
    the same 8x8 bit matrix so it stays table-free on TPU.
    """
    from ceph_tpu.gf.tables import mul_bitmatrix

    m = jnp.asarray(mul_bitmatrix(c))
    orig_shape = x.shape
    flat = x.reshape(-1, 1, orig_shape[-1]) if x.ndim > 1 else x.reshape(1, 1, -1)
    y = gf_encode_bitplane(m, flat)
    return y.reshape(orig_shape)
