"""Pallas TPU kernel: fused bit-plane GF(2^8) encode.

The XLA einsum path (ops/bitplane.py) is already well fused; this
kernel buys the rest by shaping the work for the MXU explicitly. Per
VMEM tile: load [K, T] uint8 data, unpack to plane-major bit blocks in
registers, one int8 MXU matmul against the GF(2) coding matrix, take
parity-of-count, pack, store [M, T] uint8 — HBM traffic is exactly
data-in + parity-out.

Two Mosaic/TPU realities shape the code:

- Sub-32-bit vectors can neither gain minor dims nor be shifted, so
  bit twiddling happens in int32 and the bit planes are laid out
  PLANE-MAJOR as 2-D concatenations; the coding matrix is row/column
  permuted host-side to match (``_plane_major_bitmatrix``).
- Tile size on the chunk (lane) axis is the dominant knob: the r1
  kernel used 2 KB tiles and a FOLD=4 block-diagonal matmul (73 GB/s
  claimed, 54 measured end-to-end). Sweeping on v5e showed large lane
  tiles beat folding outright — fold=1 @ 16-64 KB tiles sustains
  85-89 GB/s data-in vs 57 GB/s for fold=4 @ 2 KB; fold>1 never wins
  once tiles exceed 8 KB. Default is now fold=1 with the largest
  power-of-two tile <= 64 KB that divides the chunk ("MXU waste" was
  the wrong mental model: the [32, 64] matmul streams fine along the
  lane axis; grid-step overhead was the real cost).

Falls back to the einsum path off-TPU; unit tests run the kernel in
interpreter mode so CPU CI covers it bit-exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

LANE_TILE = 2048       # minimum chunk-axis granularity the kernel accepts
MAX_LANE_TILE = 65536  # largest tile worth using (sweep-flat above 16K)
FOLD = 1               # chunk fractions per MXU call (1 = no folding)


def _pick_lane_tile(n: int) -> int:
    """Largest power-of-two tile <= MAX_LANE_TILE dividing the chunk."""
    t = MAX_LANE_TILE
    while t > LANE_TILE and n % t:
        t //= 2
    return t


def _plane_major_bitmatrix(bitmatrix: np.ndarray, k: int, m: int) -> np.ndarray:
    """Permute [m*8, k*8] from shard-major (row j*8+b, col i*8+b) to
    plane-major (row b*m+j, col b*k+i) index order."""
    b = np.asarray(bitmatrix)
    rows = [j * 8 + bit for bit in range(8) for j in range(m)]
    cols = [i * 8 + bit for bit in range(8) for i in range(k)]
    return np.ascontiguousarray(b[np.ix_(rows, cols)])


def _folded_bitmatrix(bitmatrix: np.ndarray, fold: int) -> np.ndarray:
    """block_diag(fold copies) of the plane-major matrix: ``fold``
    independent chunk sub-tiles share one MXU pass."""
    m8, k8 = bitmatrix.shape
    pm = _plane_major_bitmatrix(bitmatrix, k8 // 8, m8 // 8)
    big = np.zeros((fold * m8, fold * k8), np.uint8)
    for f in range(fold):
        big[f * m8 : (f + 1) * m8, f * k8 : (f + 1) * k8] = pm
    return big


def _make_kernel(fold: int):
    def kernel(bmat_ref, data_ref, out_ref):
        # Bit twiddling in int32 (Mosaic has no sub-32-bit shifts);
        # only the MXU operands narrow to int8.
        d = data_ref[0].astype(jnp.int32)  # [K, T]
        t = d.shape[1]
        q = t // fold
        blocks = []
        for f in range(fold):
            dq = d[:, f * q : (f + 1) * q]
            for b in range(8):
                blocks.append(
                    ((dq >> jnp.int32(b)) & jnp.int32(1)).astype(jnp.int8)
                )
        bits = jnp.concatenate(blocks, axis=0)  # [fold*8K, q]
        acc = jnp.dot(
            bmat_ref[:].astype(jnp.int8),
            bits,
            preferred_element_type=jnp.int32,
        )  # [fold*8M, q], plane-major rows per fold block
        m = out_ref.shape[1]
        outs = []
        for f in range(fold):
            a = acc[f * 8 * m : (f + 1) * 8 * m]
            o = a[0:m] & jnp.int32(1)
            for b in range(1, 8):
                o = o | (
                    (a[b * m : (b + 1) * m] & jnp.int32(1)) << jnp.int32(b)
                )
            outs.append(o)
        out_ref[0] = jnp.concatenate(outs, axis=1).astype(jnp.uint8)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("fold", "lane_tile", "interpret")
)
def _encode_tiled(bmat_big, data, fold, lane_tile=None, interpret=False):
    batch, k, n = data.shape
    m = bmat_big.shape[0] // 8 // fold
    if lane_tile is None:
        lane_tile = _pick_lane_tile(n)
    return pl.pallas_call(
        _make_kernel(fold),
        grid=(batch, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat_big.shape, lambda b, c: (0, 0)),
            pl.BlockSpec((1, k, lane_tile), lambda b, c: (b, 0, c)),
        ],
        out_specs=pl.BlockSpec((1, m, lane_tile), lambda b, c: (b, 0, c)),
        out_shape=jax.ShapeDtypeStruct((batch, m, n), jnp.uint8),
        interpret=interpret,
    )(bmat_big, data)


def supported(data_shape: tuple[int, ...]) -> bool:
    """Kernel preconditions: [B, K, N] with the chunk axis tileable."""
    return len(data_shape) == 3 and data_shape[-1] % LANE_TILE == 0


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


@functools.lru_cache(maxsize=64)
def _folded_cached(bitmatrix_bytes: bytes, m8: int, k8: int, fold: int):
    mat = np.frombuffer(bitmatrix_bytes, np.uint8).reshape(m8, k8)
    return jnp.asarray(_folded_bitmatrix(mat, fold))


def gf_encode_bitplane_pallas(
    bitmatrix,
    data: jax.Array,
    interpret: bool | None = None,
    fold: int = FOLD,
) -> jax.Array:
    """Fused-tile encode; same contract as
    ``ops.bitplane.gf_encode_bitplane`` for [B, K, N] inputs.
    ``bitmatrix`` must be a concrete array (host-permuted once)."""
    if interpret is None:
        interpret = not on_tpu()
    mat = np.asarray(bitmatrix, dtype=np.uint8)
    big = _folded_cached(mat.tobytes(), *mat.shape, fold)
    return _encode_tiled(big, data, fold, interpret=interpret)
