"""Pallas TPU kernel: fused bit-plane GF(2^8) matrix apply.

One generic kernel serves encode, decode and delta application — any
[R*8, C*8] GF(2) bitmatrix over [B, C, N] uint8 shards (the
ErasureCodeInterface encode_chunks/decode_chunks contract,
erasure-code/ErasureCodeInterface.h:449,571; the hot loop under
osd/ECUtil.cc:487-511).

v5 design (round 6): ZERO-WASTE packing. Rounds 3-5 paired two
stripes block-diagonally in the contraction ([8·2R, 8·2C] with the
cross-stripe blocks zero), which doubled rows AND contraction so half
the clocked MACs were structural zeros — mxu_util_frac read 0.761
while useful utilization was ~0.38 (VERDICT r6 item #2/weak #3).
The v5 layout removes the tax:

- **The stationary matrix IS the code matrix.** [8R, 8F] with
  F = C + pad (pad only to the int32 sublane granularity the packed
  unpack needs, F % 4 == 0) — no stripe duplication, no block
  diagonal. Every MAC outside the pad columns touches real data:
  useful_frac = C/F (1.0 for the flagship C=8 and every C % 4 == 0
  family; see ``mac_stats``).
- **Stripes batch on the grid and the LANE axis, not the
  contraction.** Each grid step carries S stripes; their bit planes
  are unpacked per stripe and concatenated along lanes into one
  [8F, S·T] operand, so one stationary matmul streams S·T columns.
  The MXU column stream per step is as long as the old stripe-pair
  layout's, but MACs per data byte drop 2x (512 -> 256 at (8,4)) —
  the compute-bound families get their ceiling back. S is a pure
  tuning knob (lane width), not a matrix-shape choice: wide chunks
  take S=1 (pure grid batching), narrow chunks merge up to 8 stripes
  to keep ~64 KiB of lanes per step.
- **Packed unpack / bitcast-nibble pack** carry over from v3: bytes
  are reinterpreted 4-rows-per-int32 with a sublane ``pltpu.bitcast``,
  all 8 planes extracted with one row-indexed variable shift, and the
  int32 popcounts merge to output bytes with 3 shifts+ors — no second
  matmul stream. (See git history for the v3 experiment ladder.)

Falls back to the einsum path off-TPU; unit tests run the kernel in
interpreter mode (the sublane bitcasts are emulated bit-exactly
there) so CPU CI covers it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

LANE_TILE = 2048       # minimum chunk-axis granularity the kernel accepts
MAX_LANE_TILE = 65536  # sweep-best tile (grid-step overhead flat above)
#: target combined lane width (stripes-per-step x tile) of one matmul:
#: the v3/v4 sweeps measured grid-step overhead flat above ~64 KiB of
#: lanes, and VMEM pressure grows past it (bits + int32 accumulator
#: scale with the width)
LANE_WIDTH_TARGET = 65536
FOLD = 1               # retained for API compat; superseded since v3


def _pick_lane_tile(n: int) -> int:
    """Largest LANE_TILE-multiple <= MAX_LANE_TILE dividing the chunk.

    Not power-of-two halving: a 100 KiB chunk (the Cauchy baseline
    config) divides 51200 but no power of two above 4096 — the old
    halving search landed on a 4 KiB tile and paid 16x the grid-step
    overhead."""
    t = MAX_LANE_TILE
    while t > LANE_TILE and n % t:
        t -= LANE_TILE
    return t


def _pick_lane_batch(batch: int, tile: int) -> int:
    """Stripes merged along the lane axis per grid step.

    Powers of two dividing the stripe batch, until the combined lane
    width reaches LANE_WIDTH_TARGET: 1 MiB chunks run S=1 (the 64 KiB
    tile already fills the stream), the 4 KiB jerasure config merges
    8 stripes into a 32 KiB-wide matmul instead of paying 8 separate
    grid steps of starved columns."""
    s = 1
    while s < 8 and batch % (2 * s) == 0 and 2 * s * tile <= LANE_WIDTH_TARGET:
        s *= 2
    return s


def mac_stats(c: int, r: int) -> dict:
    """Clocked-vs-useful MAC accounting for the zero-waste packing.

    One output byte row costs an [8R, 8F] x [8F, lane] stream; per
    data byte that is 64*R*F/C MACs of which 64*R touch real data
    (the pad columns are the only structural zeros left). bench.py
    reports ``mxu_useful_util_frac`` from this — the round-5 packing
    clocked 2x this count with useful_frac 0.5 by construction."""
    pad = (-c) % 4
    f = c + pad
    return {
        "pad_cols": pad,
        "macs_per_byte": 64.0 * r * f / c,
        "useful_frac": c / f,
    }


# ---------------------------------------------------------------- legacy
# helpers kept for tests/benches that assert on the matrix layouts.
def _plane_major_bitmatrix(bitmatrix: np.ndarray, k: int, m: int) -> np.ndarray:
    """Permute [m*8, k*8] from shard-major (row j*8+b, col i*8+b) to
    plane-major (row b*m+j, col b*k+i) index order."""
    b = np.asarray(bitmatrix)
    rows = [j * 8 + bit for bit in range(8) for j in range(m)]
    cols = [i * 8 + bit for bit in range(8) for i in range(k)]
    return np.ascontiguousarray(b[np.ix_(rows, cols)])


def _folded_bitmatrix(bitmatrix: np.ndarray, fold: int) -> np.ndarray:
    """block_diag(fold copies) of the plane-major matrix — the
    round-2 layout (and the round-3..5 stripe pair at fold=2), kept
    as the structural-zero comparator for tests and MAC accounting."""
    m8, k8 = bitmatrix.shape
    pm = _plane_major_bitmatrix(bitmatrix, k8 // 8, m8 // 8)
    big = np.zeros((fold * m8, fold * k8), np.uint8)
    for f in range(fold):
        big[f * m8 : (f + 1) * m8, f * k8 : (f + 1) * k8] = pm
    return big


# ----------------------------------------------------- v5 stationary form
def _zw_matrix(bitmatrix: np.ndarray, c: int, r: int, pad: int) -> np.ndarray:
    """Zero-waste stationary matrix: the [R*8, C*8] code matrix
    reindexed for the packed unpack and nibble pack, nothing more.

    acc row  = h*(4*r) + j*4 + b2   (output bit b' = h*4 + b2)
    bits col = b*F + i, F = c + pad (pad columns stay zero)
    """
    from ceph_tpu.gf.bitmatrix import plane_major_cols

    rows = [
        j * 8 + h * 4 + b2
        for h in range(2)
        for j in range(r)
        for b2 in range(4)
    ]
    src = np.asarray(bitmatrix, dtype=np.uint8)[rows, :]
    return plane_major_cols(src, pad).astype(np.int8)


@functools.lru_cache(maxsize=128)
def _zw_matrix_cached(bitmatrix_bytes: bytes, r8: int, c8: int, pad: int):
    """NUMPY only in the cache: caching a device array built inside a
    jit trace would leak that trace's tracer into every later call
    with the same key (UnexpectedTracerError on the first eager
    encode after a traced one — the round-3 lru_cache lesson, hit
    again by exp_pack.py). pallas_call converts per call site. The
    key no longer carries the stripe count: the v5 matrix depends
    only on the code matrix and its pad, so every (batch, tile)
    combination shares one stationary upload."""
    mat = np.frombuffer(bitmatrix_bytes, np.uint8).reshape(r8, c8)
    return _zw_matrix(mat, c8 // 8, r8 // 8, pad)


#: second-level DEVICE cache for eager callers — populated ONLY with
#: concrete arrays (never under a trace), bounded like the np cache
_DEV_CACHE: "OrderedDict[tuple, jax.Array]" = None  # type: ignore


def _dev_cached(key: tuple, big_np: np.ndarray):
    global _DEV_CACHE
    from collections import OrderedDict

    if _DEV_CACHE is None:
        _DEV_CACHE = OrderedDict()
    dev = _DEV_CACHE.get(key)
    if dev is None:
        dev = jnp.asarray(big_np)
        _DEV_CACHE[key] = dev
        if len(_DEV_CACHE) > 128:
            _DEV_CACHE.popitem(last=False)
    else:
        _DEV_CACHE.move_to_end(key)
    return dev


# -------------------------------------------------------------- the kernel
def _emulate_rows_to_i32(x):
    """Interpret-mode stand-in for pltpu.bitcast(u8 -> i32): 4 sublane
    rows pack little-endian into one int32 row (measured hardware
    order — the nibble pack depends on it)."""
    rows, t = x.shape
    g = x.reshape(rows // 4, 4, t).astype(jnp.uint32)
    xi = g[:, 0] | (g[:, 1] << 8) | (g[:, 2] << 16) | (g[:, 3] << 24)
    return jax.lax.bitcast_convert_type(xi, jnp.int32)


def _emulate_i32_to_i8(p):
    """Inverse direction: int32 row r unpacks to int8 rows 4r+j."""
    rows, t = p.shape
    u = jax.lax.bitcast_convert_type(p, jnp.uint32)
    parts = [((u >> (8 * j)) & jnp.uint32(0xFF)) for j in range(4)]
    stacked = jnp.stack(parts, axis=1).reshape(4 * rows, t)
    return stacked.astype(jnp.int8)


def _emulate_i8_to_i32(x):
    rows, t = x.shape
    g = x.astype(jnp.uint8).reshape(rows // 4, 4, t).astype(jnp.uint32)
    xi = g[:, 0] | (g[:, 1] << 8) | (g[:, 2] << 16) | (g[:, 3] << 24)
    return jax.lax.bitcast_convert_type(xi, jnp.int32)


def bitcast_u8_to_i32(x, interpret: bool):
    """In-kernel sublane bitcast: [R, T] uint8 -> [R/4, T] int32 (4
    sublane rows pack little-endian per lane).  The shared seam for
    every kernel doing packed-byte GF arithmetic (clay_kernels, the
    plane unpack below): interpret mode emulates the measured
    hardware pack bit-exactly, so CPU CI covers the same math."""
    if interpret:
        return _emulate_i8_to_i32(x)
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.bitcast(x, jnp.int32)


def bitcast_i32_to_u8(p, interpret: bool):
    """Inverse direction: [R, T] int32 -> [4R, T] uint8."""
    if interpret:
        return _emulate_i32_to_i8(p).astype(jnp.uint8)
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.bitcast(p, jnp.int8).astype(jnp.uint8)


def unpack_bitplanes(flat, interpret: bool):
    """In-kernel bit-plane unpack shared by the EC and CRC kernels.

    ``flat`` is [F, T] uint8 with F % 4 == 0. Returns [8F, T] int8
    bit planes in (plane, row) order: a sublane bitcast packs 4 rows
    per int32 lane, ONE variable shift over 8 b-major replicas
    (row-indexed iota) extracts every plane, and the bitcast back
    scatters each byte's bit to the row it came from. Interpret mode
    emulates the measured little-endian sublane pack bit-exactly."""
    from jax.experimental.pallas import tpu as pltpu

    f, t = flat.shape
    if interpret:
        xi = _emulate_rows_to_i32(flat)
    else:
        xi = pltpu.bitcast(flat, jnp.int32)  # [F/4, T]
    X = jnp.concatenate([xi] * 8, axis=0)  # [2F, T]
    shifts = jax.lax.broadcasted_iota(
        jnp.int32, (2 * f, t), 0
    ) // jnp.int32(f // 4)  # row group F/4 rows per plane
    pb = (X >> shifts) & jnp.int32(0x01010101)
    if interpret:
        return _emulate_i32_to_i8(pb)
    return pltpu.bitcast(pb, jnp.int8)  # [8F, T]


def _unpack_stripe_lanes(stripes, pad, interpret: bool):
    """Unpack each [C, T] stripe to bit planes and merge along lanes.

    The heart of the zero-waste layout: stripes land side by side on
    the LANE axis ([8F, S*T]) instead of block-diagonally in the
    contraction, so the stationary matrix stays the [8R, 8F] code
    matrix and every contraction row feeds real data. The lane concat
    is free (tiles are lane-aligned); the per-stripe unpack costs the
    same total VPU work as one fused unpack did."""
    t = stripes[0].shape[1]
    planes = []
    for flat in stripes:
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad, t), jnp.uint8)], axis=0
            )
        planes.append(unpack_bitplanes(flat, interpret))
    return planes[0] if len(planes) == 1 else jnp.concatenate(planes, axis=1)


def _matmul_pack(bmat, bits, r, interpret: bool):
    """[8R, 8F] @ [8F, W] -> packed [R, W] uint8 output bytes via the
    bitcast-nibble pack (no second matmul stream)."""
    from jax.experimental.pallas import tpu as pltpu

    acc = jax.lax.dot_general(
        bmat, bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [8R, W] rows (h, j, b2)
    acc8 = acc.astype(jnp.int8)  # parity lives in bit 0; truncation safe
    if interpret:
        p32 = _emulate_i8_to_i32(acc8)
    else:
        p32 = pltpu.bitcast(acc8, jnp.int32)  # [2R, W]
    masked = p32 & jnp.int32(0x01010101)
    nib = (
        masked
        | (masked >> jnp.int32(7))
        | (masked >> jnp.int32(14))
        | (masked >> jnp.int32(21))
    ) & jnp.int32(0xF)
    out32 = nib[0:r] | (nib[r : 2 * r] << jnp.int32(4))
    return out32.astype(jnp.uint8)  # [R, W]


def _make_kernel(c: int, r: int, s: int, pad: int, interpret: bool):
    def kernel(bmat_ref, data_ref, out_ref):
        d = data_ref[:]  # [S, C, T] uint8
        t = d.shape[2]
        bits = _unpack_stripe_lanes(
            [d[si] for si in range(s)], pad, interpret
        )  # [8F, S*T]
        out8 = _matmul_pack(bmat_ref[:], bits, r, interpret)  # [R, S*T]
        if s == 1:
            out_ref[:] = out8.reshape(1, r, t)
        else:
            for si in range(s):
                out_ref[si] = out8[:, si * t : (si + 1) * t]

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("c", "r", "s", "pad", "lane_tile", "interpret"),
)
def _apply_tiled(bmat_big, data, c, r, s, pad, lane_tile, interpret=False):
    batch, _, n = data.shape
    return pl.pallas_call(
        _make_kernel(c, r, s, pad, interpret),
        grid=(batch // s, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat_big.shape, lambda b, ch: (0, 0)),
            pl.BlockSpec((s, c, lane_tile), lambda b, ch: (b, 0, ch)),
        ],
        out_specs=pl.BlockSpec((s, r, lane_tile), lambda b, ch: (b, 0, ch)),
        out_shape=jax.ShapeDtypeStruct((batch, r, n), jnp.uint8),
        interpret=interpret,
    )(bmat_big, data)


def supported(data_shape: tuple[int, ...]) -> bool:
    """Kernel preconditions: [B, C, N] with the chunk axis tileable."""
    return len(data_shape) == 3 and data_shape[-1] % LANE_TILE == 0


# ----------------------------------------------------------- shards form
#: block rows per grid step (sublane granularity: a 2D block's
#: second-minor dim must be a multiple of 8 or the whole axis)
SHARDS_SB = 8
#: shards-form lane-tile cap: 64 KiB tiles crashed the remote Mosaic
#: compiler at c=8 and measured no better than 32 KiB where they
#: compiled (experiments/exp_r5_byteshards2.py)
SHARDS_MAX_TILE = 32768
#: widest contraction the shards form serves (F <= 16, one clean MXU
#: pass); wider codes take the stacked kernel, which tiles the
#: contraction itself
SHARDS_MAX_C = 16


def _shards_lane_batch(tile: int) -> int:
    """Stripes per matmul group, merged along lanes (power of two
    dividing SHARDS_SB) — same LANE_WIDTH_TARGET rule as the stacked
    kernel. With zero-waste packing the group size no longer bends
    the matrix shape, so every c <= SHARDS_MAX_C rides the shards
    form (the round-5 s*c <= 16 rule shut out c > 8 entirely and sent
    cauchy/shec decode through the stacked relayout copy)."""
    s = 1
    while s < SHARDS_SB and 2 * s * tile <= LANE_WIDTH_TARGET:
        s *= 2
    return s


def shards_supported(c: int, shape: tuple[int, ...]) -> bool:
    """Can the shards-form kernel serve c per-shard [..., N] arrays?"""
    if len(shape) < 1 or not 0 < c <= SHARDS_MAX_C:
        return False
    n = shape[-1]
    b = int(np.prod(shape[:-1], initial=1))
    return b % SHARDS_SB == 0 and n % LANE_TILE == 0


def _shards_tile(n: int) -> int:
    t = min(SHARDS_MAX_TILE, n)
    while t > LANE_TILE and n % t:
        t -= LANE_TILE
    return t


@functools.lru_cache(maxsize=128)
def _shards_fn(
    mat_bytes: bytes, r8: int, c8: int, s: int, tile: int,
    interpret: bool,
):
    """Jitted shards-form apply, cached per (bitmatrix, geometry).

    The kernel carries SB stripes of every shard per block and loops
    over SB/s groups; each group gathers one [C, T] slice per stripe,
    lane-concats the unpacked planes and runs ONE stationary matmul
    with the zero-waste [8R, 8F] matrix — no per-row sublane gathers,
    no block diagonal. Output bytes come back stripe-major along
    lanes and land in m separate parity refs: neither input nor
    output is ever stacked in HBM, which is the whole win (the
    [B, k, N] stack is a relayout copy measured at 3.5x the kernel's
    own cost on the SHEC/LRC bench geometry)."""
    bitmatrix = np.frombuffer(mat_bytes, np.uint8).reshape(r8, c8)
    c, r = c8 // 8, r8 // 8
    pad = (-c) % 4
    groups = SHARDS_SB // s
    big = _zw_matrix(bitmatrix, c, r, pad)

    def kernel(bmat_ref, *refs):
        ins, outs = refs[:c], refs[c:]
        t = ins[0].shape[1]
        for g in range(groups):
            stripes = []
            for si in range(s):
                q = g * s + si
                stripes.append(jnp.concatenate(
                    [ins[i][q : q + 1, :] for i in range(c)], axis=0
                ))  # [C, T]
            bits = _unpack_stripe_lanes(stripes, pad, interpret)
            out8 = _matmul_pack(bmat_ref[:], bits, r, interpret)
            for si in range(s):
                q = g * s + si
                for j in range(r):
                    outs[j][q : q + 1, :] = out8[
                        j : j + 1, si * t : (si + 1) * t
                    ]

    @jax.jit
    def apply(bmat, *shards):
        b, n = shards[0].shape
        return pl.pallas_call(
            kernel,
            grid=(b // SHARDS_SB, n // tile),
            in_specs=[pl.BlockSpec(big.shape, lambda i, ch: (0, 0))]
            + [
                pl.BlockSpec((SHARDS_SB, tile), lambda i, ch: (i, ch))
                for _ in range(c)
            ],
            out_specs=[
                pl.BlockSpec((SHARDS_SB, tile), lambda i, ch: (i, ch))
                for _ in range(r)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, n), jnp.uint8)
                for _ in range(r)
            ],
            interpret=interpret,
        )(bmat, *shards)

    return apply, big


def gf_encode_bitplane_pallas_shards(
    bitmatrix,
    shards: list,
    interpret: bool | None = None,
) -> list:
    """Shards-form bitmatrix apply: c per-shard [..., N] arrays in,
    R = rows/8 per-shard parity arrays out — same math as
    ``gf_encode_bitplane_pallas`` with neither side ever stacked.
    Callers gate with ``shards_supported``."""
    if interpret is None:
        interpret = not on_tpu()
    mat = np.ascontiguousarray(np.asarray(bitmatrix, dtype=np.uint8))
    r8, c8 = mat.shape
    lead = shards[0].shape[:-1]
    n = shards[0].shape[-1]
    if c8 != len(shards) * 8:
        raise ValueError(
            f"bitmatrix cols {c8} != shards*8 {len(shards) * 8}"
        )
    tile = _shards_tile(n)
    s = _shards_lane_batch(tile)
    key = (mat.tobytes(), r8, c8, s, tile, interpret)
    fn, big = _shards_fn(*key)
    traced = any(isinstance(v, jax.core.Tracer) for v in shards)
    if not traced:
        big = _dev_cached(("zw-shards",) + key[:-1], big)
    b = int(np.prod(lead, initial=1))
    flat = [jnp.asarray(v).reshape(b, n) for v in shards]
    outs = fn(big, *flat)
    return [o.reshape(lead + (n,)) for o in outs]


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ------------------------------------------------- fused encode+checksum
# One device pass for the whole write path: while each stripe's data
# tiles are resident for the encode matmul, fold per-csum-block CRC32C
# for the k data shards from the SAME bit planes the matmul consumes,
# and fold the freshly-produced parity tiles before they leave VMEM —
# [shards, nblocks] u32 csums emitted alongside parity in one
# pallas_call. The separate checksum pass (which re-read every byte
# encode just wrote) disappears; at hbm_roofline_frac ~0.34 the write
# path is bandwidth-bound, so that second HBM pass was the bill.
#
# The fold reuses checksum/pallas_crc's table machinery
# (plane_fold_kb): per plane b a stationary [cb, 32] matrix whose row
# p holds the crc-register contribution of bit b of byte p — the CRC
# of one block is 8 extra [rows, cb] @ kb[b] MXU dots over bits the
# kernel already holds. Csums come out ZERO-INIT; any seed is a
# constant XOR on the host (checksum.crc32c.crc32c_seed_shift), so
# one kernel output serves BlueStore blob csums (seed -1), HashInfo
# chaining, and wire csums alike.


@functools.lru_cache(maxsize=8)
def _kb_cached(csum_block: int) -> np.ndarray:
    """NUMPY only (the _zw_matrix_cached trace-safety rule)."""
    from ceph_tpu.checksum.pallas_crc import plane_fold_kb

    return plane_fold_kb(csum_block)


def _crc_fold_tile(
    planes, parity8, kb_ref, c, f, r, rp, cb, interpret: bool
):
    """CRC32C fold epilogue for ONE stripe's resident tile.

    ``planes`` are the data bit planes the encode matmul just consumed
    ([8F, T], plane-major); ``parity8`` the packed parity bytes
    ([R, T]) — unpacked once more in registers (rows padded to the
    int32 sublane granularity), never via HBM. Returns [C+R, nb*32]
    int32 fold counts: per csum block q and plane b one
    [C+R, cb] @ kb[b] dot, summed over the 8 planes — contraction cb,
    exactly the pallas_crc discipline, minus its unpack (already
    paid) and minus its HBM read (the data never left VMEM)."""
    t = parity8.shape[1]
    if rp > r:
        parity8 = jnp.concatenate(
            [parity8, jnp.zeros((rp - r, t), jnp.uint8)], axis=0
        )
    pplanes = unpack_bitplanes(parity8, interpret)  # [8*rp, T]
    nb = t // cb
    accs = []
    for q in range(nb):
        lo = q * cb
        acc = None
        for b in range(8):
            rows = jnp.concatenate(
                [
                    planes[b * f : b * f + c, lo : lo + cb],
                    pplanes[b * rp : b * rp + r, lo : lo + cb],
                ],
                axis=0,
            )  # [C+R, cb] bits of plane b
            part = jax.lax.dot_general(
                rows, kb_ref[b],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [C+R, 32]
            acc = part if acc is None else acc + part
        accs.append(acc)
    return accs[0] if nb == 1 else jnp.concatenate(accs, axis=1)


def _csum_pack(acc, c, r, cb):
    """[B, C+R, (N/cb)*32] int32 fold counts -> [B, C+R, N/cb] uint32
    zero-init csums (mod 2 + LSB-first bit pack) — the tiny epilogue
    outside the kernel, same as pallas_crc's."""
    batch = acc.shape[0]
    bits = (acc.reshape(batch, c + r, -1, 32) & 1).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(bits * weights, axis=-1, dtype=jnp.uint32)


def _make_fused_kernel(c, r, s, pad, cb, interpret: bool):
    f = c + pad
    rp = -(-r // 4) * 4

    def kernel(bmat_ref, kb_ref, data_ref, out_ref, csum_ref):
        d = data_ref[:]  # [S, C, T] uint8
        t = d.shape[2]
        planes = []
        for si in range(s):
            flat = d[si]
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad, t), jnp.uint8)], axis=0
                )
            planes.append(unpack_bitplanes(flat, interpret))
        bits = planes[0] if s == 1 else jnp.concatenate(planes, axis=1)
        out8 = _matmul_pack(bmat_ref[:], bits, r, interpret)  # [R, S*T]
        nb = t // cb
        for si in range(s):
            tile = out8[:, si * t : (si + 1) * t]
            fold = _crc_fold_tile(
                planes[si], tile, kb_ref, c, f, r, rp, cb, interpret
            )
            if s == 1:
                out_ref[:] = tile.reshape(1, r, t)
                csum_ref[:] = fold.reshape(1, c + r, nb * 32)
            else:
                out_ref[si] = tile
                csum_ref[si] = fold

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("c", "r", "s", "pad", "lane_tile", "cb", "interpret"),
)
def _apply_tiled_csum(
    bmat_big, kb, data, c, r, s, pad, lane_tile, cb, interpret=False
):
    batch, _, n = data.shape
    nb = lane_tile // cb
    parity, acc = pl.pallas_call(
        _make_fused_kernel(c, r, s, pad, cb, interpret),
        grid=(batch // s, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat_big.shape, lambda b, ch: (0, 0)),
            pl.BlockSpec(kb.shape, lambda b, ch: (0, 0, 0)),
            pl.BlockSpec((s, c, lane_tile), lambda b, ch: (b, 0, ch)),
        ],
        out_specs=[
            pl.BlockSpec((s, r, lane_tile), lambda b, ch: (b, 0, ch)),
            pl.BlockSpec((s, c + r, nb * 32), lambda b, ch: (b, 0, ch)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, r, n), jnp.uint8),
            jax.ShapeDtypeStruct(
                (batch, c + r, (n // cb) * 32), jnp.int32
            ),
        ],
        interpret=interpret,
    )(bmat_big, kb, data)
    return parity, _csum_pack(acc, c, r, cb)


def fused_csum_supported(data_shape: tuple[int, ...], csum_block: int) -> bool:
    """Stacked-form gate: the encode kernel's own preconditions plus a
    csum block that the lane tiling can respect (power of two >= 256
    dividing the chunk axis)."""
    return (
        supported(data_shape)
        and csum_block >= 256
        and csum_block & (csum_block - 1) == 0
        and data_shape[-1] % csum_block == 0
    )


def _pick_fused_tile(n: int, cb: int, cap: int = MAX_LANE_TILE) -> int:
    """Largest tile <= cap that divides the chunk AND is a multiple of
    both the lane granularity and the csum block (so every csum block
    lives wholly inside one grid step — no cross-step accumulator)."""
    step = max(cb, LANE_TILE)
    t = max(step, (min(cap, n) // step) * step)
    while t > step and n % t:
        t -= step
    return t


def gf_encode_csum_bitplane_pallas(
    bitmatrix,
    data: jax.Array,
    csum_block: int,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Fused encode+checksum: same parity as
    ``gf_encode_bitplane_pallas`` PLUS ``[B, C+R, N//csum_block]``
    uint32 ZERO-INIT per-block CRC32C csums (rows 0..C-1 = the data
    shards in input order, C..C+R-1 = the parity rows), all from one
    pallas_call. Callers gate with ``fused_csum_supported``."""
    if interpret is None:
        interpret = not on_tpu()
    mat = np.ascontiguousarray(np.asarray(bitmatrix, dtype=np.uint8))
    r8, c8 = mat.shape
    batch, c, n = data.shape
    if c8 != c * 8:
        raise ValueError(f"bitmatrix cols {c8} != shards*8 {c * 8}")
    if not fused_csum_supported(data.shape, csum_block):
        raise ValueError(
            f"shape {data.shape} x csum_block {csum_block} untileable"
        )
    pad = (-c) % 4
    key = (mat.tobytes(), r8, c8, pad)
    big = _zw_matrix_cached(*key)
    kb = _kb_cached(csum_block)
    r = r8 // 8
    f = c + pad
    # the fused epilogue adds the kb fold table (8*cb*32 int8) and the
    # parity bit planes to the plain kernel's VMEM budget, and traced
    # callers cannot retry a failed compile — cap the tile at the
    # shards-form 32 KiB (measured no slower than 64 KiB where both
    # compiled), with the plain kernel's wide-contraction shrink on top
    cap = SHARDS_MAX_TILE if f <= 32 else max(
        max(csum_block, LANE_TILE), (65536 * 32) // f
    )
    tile = _pick_fused_tile(n, csum_block, cap)
    s = _pick_lane_batch(batch, tile)
    if not isinstance(data, jax.core.Tracer):
        big = _dev_cached(key, big)
        kb = _dev_cached(("kb", csum_block), kb)
    else:
        return _apply_tiled_csum(
            big, kb, data, c, r, s, pad, tile, csum_block,
            interpret=interpret,
        )
    step = max(csum_block, LANE_TILE)
    while True:  # the eager compile-failure retry of the plain kernel
        try:
            return _apply_tiled_csum(
                big, kb, data, c, r, s, pad, tile, csum_block,
                interpret=interpret,
            )
        except Exception:
            if s > 1:
                s //= 2
            elif tile > step:
                tile = _pick_fused_tile(n, csum_block, tile - step)
            else:
                raise


# -- shards form --------------------------------------------------------
def fused_csum_shards_supported(
    c: int, shape: tuple[int, ...], csum_block: int
) -> bool:
    return (
        shards_supported(c, shape)
        and 256 <= csum_block <= SHARDS_MAX_TILE
        and csum_block & (csum_block - 1) == 0
        and shape[-1] % csum_block == 0
    )


@functools.lru_cache(maxsize=64)
def _shards_csum_fn(
    mat_bytes: bytes, r8: int, c8: int, s: int, tile: int, cb: int,
    interpret: bool,
):
    """Fused shards-form apply: the zero-waste shards kernel
    (_shards_fn) with the CRC fold epilogue per stripe — parity lands
    in R per-shard refs, csums in one [B, C+R, (N/cb)*32] accumulator,
    neither inputs nor outputs ever stacked in HBM."""
    bitmatrix = np.frombuffer(mat_bytes, np.uint8).reshape(r8, c8)
    c, r = c8 // 8, r8 // 8
    pad = (-c) % 4
    f = c + pad
    rp = -(-r // 4) * 4
    groups = SHARDS_SB // s
    big = _zw_matrix(bitmatrix, c, r, pad)
    kb_np = _kb_cached(cb)
    nb = tile // cb

    def kernel(bmat_ref, kb_ref, *refs):
        ins = refs[:c]
        outs = refs[c : c + r]
        csum_ref = refs[c + r]
        t = ins[0].shape[1]
        for g in range(groups):
            planes = []
            for si in range(s):
                q = g * s + si
                flat = jnp.concatenate(
                    [ins[i][q : q + 1, :] for i in range(c)], axis=0
                )
                if pad:
                    flat = jnp.concatenate(
                        [flat, jnp.zeros((pad, t), jnp.uint8)], axis=0
                    )
                planes.append(unpack_bitplanes(flat, interpret))
            bits = (
                planes[0] if s == 1
                else jnp.concatenate(planes, axis=1)
            )
            out8 = _matmul_pack(bmat_ref[:], bits, r, interpret)
            for si in range(s):
                q = g * s + si
                tile_o = out8[:, si * t : (si + 1) * t]
                for j in range(r):
                    outs[j][q : q + 1, :] = tile_o[j : j + 1, :]
                csum_ref[q] = _crc_fold_tile(
                    planes[si], tile_o, kb_ref, c, f, r, rp, cb,
                    interpret,
                )

    @jax.jit
    def apply(bmat, kb, *shards):
        b, n = shards[0].shape
        outs = pl.pallas_call(
            kernel,
            grid=(b // SHARDS_SB, n // tile),
            in_specs=[
                pl.BlockSpec(big.shape, lambda i, ch: (0, 0)),
                pl.BlockSpec(kb_np.shape, lambda i, ch: (0, 0, 0)),
            ]
            + [
                pl.BlockSpec((SHARDS_SB, tile), lambda i, ch: (i, ch))
                for _ in range(c)
            ],
            out_specs=[
                pl.BlockSpec((SHARDS_SB, tile), lambda i, ch: (i, ch))
                for _ in range(r)
            ]
            + [
                pl.BlockSpec(
                    (SHARDS_SB, c + r, nb * 32), lambda i, ch: (i, 0, ch)
                )
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, n), jnp.uint8)
                for _ in range(r)
            ]
            + [
                jax.ShapeDtypeStruct(
                    (b, c + r, (n // cb) * 32), jnp.int32
                )
            ],
            interpret=interpret,
        )(bmat, kb, *shards)
        return list(outs[:r]) + [_csum_pack(outs[r], c, r, cb)]

    return apply, big, kb_np


def gf_encode_csum_bitplane_pallas_shards(
    bitmatrix,
    shards: list,
    csum_block: int,
    interpret: bool | None = None,
) -> tuple[list, jax.Array]:
    """Shards-form fused encode+checksum: c per-shard [..., N] arrays
    in; (R per-shard parity arrays, [B, C+R, N//csum_block] uint32
    zero-init csums) out. Callers gate with
    ``fused_csum_shards_supported``."""
    if interpret is None:
        interpret = not on_tpu()
    mat = np.ascontiguousarray(np.asarray(bitmatrix, dtype=np.uint8))
    r8, c8 = mat.shape
    lead = shards[0].shape[:-1]
    n = shards[0].shape[-1]
    if c8 != len(shards) * 8:
        raise ValueError(
            f"bitmatrix cols {c8} != shards*8 {len(shards) * 8}"
        )
    tile = _pick_fused_tile(n, csum_block, SHARDS_MAX_TILE)
    s = _shards_lane_batch(tile)
    key = (mat.tobytes(), r8, c8, s, tile, csum_block, interpret)
    fn, big, kb = _shards_csum_fn(*key)
    traced = any(isinstance(v, jax.core.Tracer) for v in shards)
    if not traced:
        big = _dev_cached(("zw-shards",) + key[:-1], big)
        kb = _dev_cached(("kb", csum_block), kb)
    b = int(np.prod(lead, initial=1))
    r = r8 // 8
    flat = [jnp.asarray(v).reshape(b, n) for v in shards]
    outs = fn(big, kb, *flat)
    parity = [o.reshape(lead + (n,)) for o in outs[:r]]
    csums = outs[r].reshape(lead + (c8 // 8 + r, n // csum_block))
    return parity, csums


def gf_encode_bitplane_pallas(
    bitmatrix,
    data: jax.Array,
    interpret: bool | None = None,
    fold: int = FOLD,
) -> jax.Array:
    """Fused-tile bitmatrix apply; same contract as
    ``ops.bitplane.gf_encode_bitplane`` for [B, C, N] inputs.
    ``bitmatrix`` must be a concrete [R*8, C*8] array (host-permuted
    once, LRU-cached). ``fold`` is accepted for API compatibility;
    the zero-waste lane batching supersedes it."""
    del fold
    if interpret is None:
        interpret = not on_tpu()
    mat = np.ascontiguousarray(np.asarray(bitmatrix, dtype=np.uint8))
    r8, c8 = mat.shape
    batch, c, n = data.shape
    if c8 != c * 8:
        raise ValueError(f"bitmatrix cols {c8} != shards*8 {c * 8}")
    pad = (-c) % 4
    key = (mat.tobytes(), r8, c8, pad)
    big = _zw_matrix_cached(*key)
    if not isinstance(data, jax.core.Tracer):
        # eager calls keep a CONCRETE device copy so the stationary
        # matrix uploads once, not per call; traced calls embed the
        # numpy constant in their own trace (caching a device array
        # built under a trace is the tracer-leak this split avoids)
        big = _dev_cached(key, big)
    r = r8 // 8
    tile = _pick_lane_tile(n)
    # VMEM pressure scales with the contraction width (8 * (C+pad)
    # int8 rows of bits plus the int32 accumulator); shrink the lane
    # tile for wide matrices up front. F <= 32 keeps the full 64K
    # width — measured FASTER there (k=32/F=32 at 64K ran 1.5x the
    # shrunken tile); only genuinely wide contractions shrink.
    f = c + pad
    if f > 32:
        while tile > LANE_TILE and tile > (65536 * 32) // f:
            tile //= 2
    s = _pick_lane_batch(batch, tile)
    if isinstance(data, jax.core.Tracer):
        # Under an outer trace the compile happens later, outside any
        # try here — no retry is possible, so go with the sized tile.
        return _apply_tiled(
            big, data, c, r, s, pad, tile, interpret=interpret
        )
    # Eager call: retry on compile failure rather than refusing
    # large k outright — shrink the combined lane width (stripes
    # first, then the tile) until it compiles.
    while True:
        try:
            return _apply_tiled(
                big, data, c, r, s, pad, tile, interpret=interpret
            )
        except Exception:
            if s > 1:
                s //= 2
            elif tile > LANE_TILE:
                tile //= 2
            else:
                raise
