"""Pallas TPU kernel: fused bit-plane GF(2^8) matrix apply.

One generic kernel serves encode, decode and delta application — any
[R*8, C*8] GF(2) bitmatrix over [B, C, N] uint8 shards (the
ErasureCodeInterface encode_chunks/decode_chunks contract,
erasure-code/ErasureCodeInterface.h:449,571; the hot loop under
osd/ECUtil.cc:487-511).

v3 design (round 3), shaped by measurement on v5e (see git history
for the experiment ladder; ~2.6x the round-2 kernel):

- **Packed unpack.** Bytes are reinterpreted 4-rows-per-int32 with a
  sublane `pltpu.bitcast` (free: the int8 vreg IS the packed int32
  vreg), then all 8 bit planes are extracted with ONE variable-shift
  op: the int32 rows are replicated 8x (b-major), a row-indexed iota
  supplies the per-replica shift, and `(X >> iota) & 0x01010101`
  yields every plane in a single masked shift. A second bitcast back
  to int8 lands the planes in exactly the (plane, stripe, shard) row
  order the matmul wants — the unpack never touches partial tiles
  and the concat is free.
- **One MXU pass, contraction 128.** Two stripes share the matmul
  ([8RS, 8CS] block-diagonal, contraction 8*C*S = 128 for the
  flagship (8,4)): a streamed column carries 16 data bytes, double
  the naive per-stripe kernel — the MXU stream, not its FLOPs, is
  what the bit-plane formulation pays for.
- **Bitcast-nibble pack.** The int32 popcounts are narrowed to int8,
  bitcast so 4 parity bits share an int32 lane, and merged with 3
  shifts+ors — no second matmul stream (the round-2 pack burned a
  full extra MXU pass re-streaming the accumulator).

Sweep on v5e: ~224 GB/s data-in EC(8,4) at 64 KiB lane tiles (41% of
the 819 GB/s HBM roofline; traffic = 1.5x data at m/k = 0.5), vs
87 GB/s for the round-2 fold kernel and 54 for round 1.

Falls back to the einsum path off-TPU; unit tests run the kernel in
interpreter mode (the sublane bitcasts are emulated bit-exactly
there) so CPU CI covers it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

LANE_TILE = 2048       # minimum chunk-axis granularity the kernel accepts
MAX_LANE_TILE = 65536  # sweep-best tile (grid-step overhead flat above)
FOLD = 1               # retained for API compat; the v3 kernel ignores it


def _pick_lane_tile(n: int) -> int:
    """Largest LANE_TILE-multiple <= MAX_LANE_TILE dividing the chunk.

    Not power-of-two halving: a 100 KiB chunk (the Cauchy baseline
    config) divides 51200 but no power of two above 4096 — the old
    halving search landed on a 4 KiB tile and paid 16x the grid-step
    overhead."""
    t = MAX_LANE_TILE
    while t > LANE_TILE and n % t:
        t -= LANE_TILE
    return t


# ---------------------------------------------------------------- legacy
# helpers kept for tests/benches that assert on the matrix layouts.
def _plane_major_bitmatrix(bitmatrix: np.ndarray, k: int, m: int) -> np.ndarray:
    """Permute [m*8, k*8] from shard-major (row j*8+b, col i*8+b) to
    plane-major (row b*m+j, col b*k+i) index order."""
    b = np.asarray(bitmatrix)
    rows = [j * 8 + bit for bit in range(8) for j in range(m)]
    cols = [i * 8 + bit for bit in range(8) for i in range(k)]
    return np.ascontiguousarray(b[np.ix_(rows, cols)])


def _folded_bitmatrix(bitmatrix: np.ndarray, fold: int) -> np.ndarray:
    """block_diag(fold copies) of the plane-major matrix."""
    m8, k8 = bitmatrix.shape
    pm = _plane_major_bitmatrix(bitmatrix, k8 // 8, m8 // 8)
    big = np.zeros((fold * m8, fold * k8), np.uint8)
    for f in range(fold):
        big[f * m8 : (f + 1) * m8, f * k8 : (f + 1) * k8] = pm
    return big


# ------------------------------------------------------------ v3 matrices
def _v3_matrix(
    bitmatrix: np.ndarray, c: int, r: int, s: int, pad: int
) -> np.ndarray:
    """Stationary matrix for the v3 kernel.

    acc row  = h*(4*s*r) + si*(4*r) + j*4 + b2   (output bit b' = h*4+b2)
    bits col = b*(s*c+pad) + si*c + i            (pad columns stay zero)
    """
    f = s * c + pad
    mat = np.zeros((8 * s * r, 8 * f), np.int8)
    for h in range(2):
        for si in range(s):
            for j in range(r):
                for b2 in range(4):
                    bp = h * 4 + b2
                    row = h * (4 * s * r) + si * (4 * r) + j * 4 + b2
                    for b in range(8):
                        for i in range(c):
                            mat[row, b * f + si * c + i] = bitmatrix[
                                j * 8 + bp, i * 8 + b
                            ]
    return mat


@functools.lru_cache(maxsize=128)
def _v3_matrix_cached(
    bitmatrix_bytes: bytes, r8: int, c8: int, s: int, pad: int
):
    """NUMPY only in the cache: caching a device array built inside a
    jit trace would leak that trace's tracer into every later call
    with the same key (UnexpectedTracerError on the first eager
    encode after a traced one — the round-3 lru_cache lesson, hit
    again by exp_pack.py). pallas_call converts per call site."""
    mat = np.frombuffer(bitmatrix_bytes, np.uint8).reshape(r8, c8)
    return _v3_matrix(mat, c8 // 8, r8 // 8, s, pad)


#: second-level DEVICE cache for eager callers — populated ONLY with
#: concrete arrays (never under a trace), bounded like the np cache
_V3_DEV: "OrderedDict[tuple, jax.Array]" = None  # type: ignore


def _v3_dev_cached(key: tuple, big_np: np.ndarray):
    global _V3_DEV
    from collections import OrderedDict

    if _V3_DEV is None:
        _V3_DEV = OrderedDict()
    dev = _V3_DEV.get(key)
    if dev is None:
        dev = jnp.asarray(big_np)
        _V3_DEV[key] = dev
        if len(_V3_DEV) > 128:
            _V3_DEV.popitem(last=False)
    else:
        _V3_DEV.move_to_end(key)
    return dev


def _pick_stripes(c: int, batch: int) -> tuple[int, int]:
    """(stripes-per-block, pad-rows) — the high-k packing rule.

    Measured on v5e (round 4, exp_highk*.py): column-stream rate is
    roughly constant per F row-block up to F=32, so throughput tracks
    useful bytes per streamed column. Winners per c:
    - 2c <= 16 (flagship and below): two stripes, contraction 8*2c
      (the round-3 layout, 305-333 GB/s at (8,4));
    - c 9..12, even batch: two stripes padded to F=24 (210-299 GB/s
      at k=10 vs 96 for the old single-stripe+pad fallback);
    - c 13..16: one stripe padded to F=16 (708 GB/s at k=16);
    - c 17..32: one stripe padded to F=32 (470 GB/s at k=21,
      736 at k=32 — Mosaic tiles the 256-contraction cleanly);
    - above 32: one stripe padded to the int32 sublane granularity
      times two (F % 8 == 0), contraction tiled by the compiler.
    """
    if batch % 2 == 0 and 2 * c <= 16 and (2 * c) % 4 == 0:
        return 2, 0
    if c <= 8:
        return 1, (-c) % 4
    if batch % 2 == 0 and c <= 12:
        return 2, (-2 * c) % 8
    if c <= 16:
        return 1, 16 - c
    if c <= 32:
        return 1, 32 - c
    return 1, (-c) % 8


# -------------------------------------------------------------- the kernel
def _emulate_rows_to_i32(x):
    """Interpret-mode stand-in for pltpu.bitcast(u8 -> i32): 4 sublane
    rows pack little-endian into one int32 row (measured hardware
    order — the nibble pack depends on it)."""
    rows, t = x.shape
    g = x.reshape(rows // 4, 4, t).astype(jnp.uint32)
    xi = g[:, 0] | (g[:, 1] << 8) | (g[:, 2] << 16) | (g[:, 3] << 24)
    return jax.lax.bitcast_convert_type(xi, jnp.int32)


def _emulate_i32_to_i8(p):
    """Inverse direction: int32 row r unpacks to int8 rows 4r+j."""
    rows, t = p.shape
    u = jax.lax.bitcast_convert_type(p, jnp.uint32)
    parts = [((u >> (8 * j)) & jnp.uint32(0xFF)) for j in range(4)]
    stacked = jnp.stack(parts, axis=1).reshape(4 * rows, t)
    return stacked.astype(jnp.int8)


def _emulate_i8_to_i32(x):
    rows, t = x.shape
    g = x.astype(jnp.uint8).reshape(rows // 4, 4, t).astype(jnp.uint32)
    xi = g[:, 0] | (g[:, 1] << 8) | (g[:, 2] << 16) | (g[:, 3] << 24)
    return jax.lax.bitcast_convert_type(xi, jnp.int32)


def unpack_bitplanes(flat, interpret: bool):
    """In-kernel bit-plane unpack shared by the EC and CRC kernels.

    ``flat`` is [F, T] uint8 with F % 4 == 0. Returns [8F, T] int8
    bit planes in (plane, row) order: a sublane bitcast packs 4 rows
    per int32 lane, ONE variable shift over 8 b-major replicas
    (row-indexed iota) extracts every plane, and the bitcast back
    scatters each byte's bit to the row it came from. Interpret mode
    emulates the measured little-endian sublane pack bit-exactly."""
    from jax.experimental.pallas import tpu as pltpu

    f, t = flat.shape
    if interpret:
        xi = _emulate_rows_to_i32(flat)
    else:
        xi = pltpu.bitcast(flat, jnp.int32)  # [F/4, T]
    X = jnp.concatenate([xi] * 8, axis=0)  # [2F, T]
    shifts = jax.lax.broadcasted_iota(
        jnp.int32, (2 * f, t), 0
    ) // jnp.int32(f // 4)  # row group F/4 rows per plane
    pb = (X >> shifts) & jnp.int32(0x01010101)
    if interpret:
        return _emulate_i32_to_i8(pb)
    return pltpu.bitcast(pb, jnp.int8)  # [8F, T]


def _make_kernel(c: int, r: int, s: int, pad: int, interpret: bool):
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bmat_ref, data_ref, out_ref):
        d = data_ref[:]  # [S, C, T] uint8
        t = d.shape[2]
        flat = d.reshape(s * c, t)
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad, t), jnp.uint8)], axis=0
            )
        bits = unpack_bitplanes(flat, interpret)  # [8F, T] (b, s, i)
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [8SR, T] rows (h, s, j, b2)
        acc8 = acc.astype(jnp.int8)  # popcounts <= 8C fit easily
        if interpret:
            p32 = _emulate_i8_to_i32(acc8)
        else:
            p32 = pltpu.bitcast(acc8, jnp.int32)  # [2SR, T]
        masked = p32 & jnp.int32(0x01010101)
        nib = (
            masked
            | (masked >> jnp.int32(7))
            | (masked >> jnp.int32(14))
            | (masked >> jnp.int32(21))
        ) & jnp.int32(0xF)
        sr = s * r
        out32 = nib[0:sr] | (nib[sr : 2 * sr] << jnp.int32(4))
        out_ref[:] = out32.astype(jnp.uint8).reshape(s, r, t)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("c", "r", "s", "pad", "lane_tile", "interpret"),
)
def _apply_tiled(bmat_big, data, c, r, s, pad, lane_tile, interpret=False):
    batch, _, n = data.shape
    return pl.pallas_call(
        _make_kernel(c, r, s, pad, interpret),
        grid=(batch // s, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat_big.shape, lambda b, ch: (0, 0)),
            pl.BlockSpec((s, c, lane_tile), lambda b, ch: (b, 0, ch)),
        ],
        out_specs=pl.BlockSpec((s, r, lane_tile), lambda b, ch: (b, 0, ch)),
        out_shape=jax.ShapeDtypeStruct((batch, r, n), jnp.uint8),
        interpret=interpret,
    )(bmat_big, data)


def supported(data_shape: tuple[int, ...]) -> bool:
    """Kernel preconditions: [B, C, N] with the chunk axis tileable."""
    return len(data_shape) == 3 and data_shape[-1] % LANE_TILE == 0


# ----------------------------------------------------------- shards form
#: block rows per grid step (sublane granularity: a 2D block's
#: second-minor dim must be a multiple of 8 or the whole axis)
SHARDS_SB = 8
#: shards-form lane-tile cap: 64 KiB tiles crashed the remote Mosaic
#: compiler at c=8 and measured no better than 32 KiB where they
#: compiled (experiments/exp_r5_byteshards2.py)
SHARDS_MAX_TILE = 32768


def _v4_matrix(
    bitmatrix: np.ndarray, c: int, r: int, s: int, pad: int
) -> np.ndarray:
    """Stationary matrix for the shards-form kernel: v3's row order
    with SHARD-MAJOR bit columns, so a group's flat input is a concat
    of contiguous per-shard [s, T] slices.

    acc row  = h*(4*s*r) + si*(4*r) + j*4 + b2   (output bit b' = h*4+b2)
    bits col = b*F + i*s + si, F = s*c + pad     (pad columns stay zero)
    """
    f = s * c + pad
    mat = np.zeros((8 * s * r, 8 * f), np.int8)
    for h in range(2):
        for si in range(s):
            for j in range(r):
                for b2 in range(4):
                    bp = h * 4 + b2
                    row = h * (4 * s * r) + si * (4 * r) + j * 4 + b2
                    for b in range(8):
                        for i in range(c):
                            mat[row, b * f + i * s + si] = bitmatrix[
                                j * 8 + bp, i * 8 + b
                            ]
    return mat


def _shards_stripes(c: int) -> int | None:
    """Stripes per matmul group: largest s with contraction 8*s*c
    <= 128 — the F=16 sweet spot the stacked-path sweep found, now
    per-shard (c=2 -> s=8 measured 284 GB/s vs 85 stacked; c=4 ->
    s=4, 147 vs 27 through the stacked codec path). c > 8 has no
    viable s and stays on the stacked kernel."""
    for s in (8, 4, 2):
        if s * c <= 16:
            return s
    return None


def shards_supported(c: int, shape: tuple[int, ...]) -> bool:
    """Can the shards-form kernel serve c per-shard [..., N] arrays?"""
    if len(shape) < 1 or _shards_stripes(c) is None:
        return False
    n = shape[-1]
    b = int(np.prod(shape[:-1], initial=1))
    return b % SHARDS_SB == 0 and n % LANE_TILE == 0


def _shards_tile(n: int) -> int:
    t = min(SHARDS_MAX_TILE, n)
    while t > LANE_TILE and n % t:
        t -= LANE_TILE
    return t


@functools.lru_cache(maxsize=128)
def _shards_fn(
    mat_bytes: bytes, r8: int, c8: int, s: int, tile: int,
    interpret: bool,
):
    """Jitted shards-form apply, cached per (bitmatrix, geometry).

    The kernel carries SB stripes of every shard per block and loops
    over SB/s groups; each group is one stationary matmul with the
    SHARD-MAJOR v4 matrix (bits col = b*F + i*s + si), so the group's
    flat input is a concat of contiguous [s, T] slices — no per-row
    sublane gathers. Output rows come back in (si, j) order and land
    in m separate parity refs: neither input nor output is ever
    stacked in HBM, which is the whole win (the [B, k, N] stack is a
    relayout copy measured at 3.5x the kernel's own cost on the
    SHEC/LRC bench geometry)."""
    from jax.experimental.pallas import tpu as pltpu

    bitmatrix = np.frombuffer(mat_bytes, np.uint8).reshape(r8, c8)
    c, r = c8 // 8, r8 // 8
    pad = (-s * c) % 4
    groups = SHARDS_SB // s
    big = _v4_matrix(bitmatrix, c, r, s, pad)

    def kernel(bmat_ref, *refs):
        ins, outs = refs[:c], refs[c:]
        t = ins[0].shape[1]
        for g in range(groups):
            parts = [ins[i][g * s : (g + 1) * s, :] for i in range(c)]
            flat = jnp.concatenate(parts, axis=0)  # [s*c, T] (i, si)
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad, t), jnp.uint8)], axis=0
                )
            bits = unpack_bitplanes(flat, interpret)
            acc = jax.lax.dot_general(
                bmat_ref[:], bits, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            acc8 = acc.astype(jnp.int8)
            if interpret:
                p32 = _emulate_i8_to_i32(acc8)
            else:
                p32 = pltpu.bitcast(acc8, jnp.int32)
            masked = p32 & jnp.int32(0x01010101)
            nib = (
                masked | (masked >> jnp.int32(7))
                | (masked >> jnp.int32(14)) | (masked >> jnp.int32(21))
            ) & jnp.int32(0xF)
            sr = s * r
            out32 = nib[0:sr] | (nib[sr : 2 * sr] << jnp.int32(4))
            out8 = out32.astype(jnp.uint8).reshape(s, r, t)
            for j in range(r):
                outs[j][g * s : (g + 1) * s, :] = out8[:, j, :]

    @jax.jit
    def apply(bmat, *shards):
        b, n = shards[0].shape
        return pl.pallas_call(
            kernel,
            grid=(b // SHARDS_SB, n // tile),
            in_specs=[pl.BlockSpec(big.shape, lambda i, ch: (0, 0))]
            + [
                pl.BlockSpec((SHARDS_SB, tile), lambda i, ch: (i, ch))
                for _ in range(c)
            ],
            out_specs=[
                pl.BlockSpec((SHARDS_SB, tile), lambda i, ch: (i, ch))
                for _ in range(r)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, n), jnp.uint8)
                for _ in range(r)
            ],
            interpret=interpret,
        )(bmat, *shards)

    return apply, big


def gf_encode_bitplane_pallas_shards(
    bitmatrix,
    shards: list,
    interpret: bool | None = None,
) -> list:
    """Shards-form bitmatrix apply: c per-shard [..., N] arrays in,
    R = rows/8 per-shard parity arrays out — same math as
    ``gf_encode_bitplane_pallas`` with neither side ever stacked.
    Callers gate with ``shards_supported``."""
    if interpret is None:
        interpret = not on_tpu()
    mat = np.ascontiguousarray(np.asarray(bitmatrix, dtype=np.uint8))
    r8, c8 = mat.shape
    lead = shards[0].shape[:-1]
    n = shards[0].shape[-1]
    if c8 != len(shards) * 8:
        raise ValueError(
            f"bitmatrix cols {c8} != shards*8 {len(shards) * 8}"
        )
    s = _shards_stripes(c8 // 8)
    key = (mat.tobytes(), r8, c8, s, _shards_tile(n), interpret)
    fn, big = _shards_fn(*key)
    traced = any(isinstance(v, jax.core.Tracer) for v in shards)
    if not traced:
        big = _v3_dev_cached(("v4",) + key[:-1], big)
    b = int(np.prod(lead, initial=1))
    flat = [jnp.asarray(v).reshape(b, n) for v in shards]
    outs = fn(big, *flat)
    return [o.reshape(lead + (n,)) for o in outs]


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def gf_encode_bitplane_pallas(
    bitmatrix,
    data: jax.Array,
    interpret: bool | None = None,
    fold: int = FOLD,
) -> jax.Array:
    """Fused-tile bitmatrix apply; same contract as
    ``ops.bitplane.gf_encode_bitplane`` for [B, C, N] inputs.
    ``bitmatrix`` must be a concrete [R*8, C*8] array (host-permuted
    once, LRU-cached). ``fold`` is accepted for API compatibility;
    the v3 kernel's stripe packing supersedes it."""
    del fold
    if interpret is None:
        interpret = not on_tpu()
    mat = np.ascontiguousarray(np.asarray(bitmatrix, dtype=np.uint8))
    r8, c8 = mat.shape
    batch, c, n = data.shape
    if c8 != c * 8:
        raise ValueError(f"bitmatrix cols {c8} != shards*8 {c * 8}")
    s, pad = _pick_stripes(c, batch)
    key = (mat.tobytes(), r8, c8, s, pad)
    big = _v3_matrix_cached(*key)
    if not isinstance(data, jax.core.Tracer):
        # eager calls keep a CONCRETE device copy so the stationary
        # matrix uploads once, not per call; traced calls embed the
        # numpy constant in their own trace (caching a device array
        # built under a trace is the tracer-leak this split avoids)
        big = _v3_dev_cached(key, big)
    r = r8 // 8
    tile = _pick_lane_tile(n)
    # VMEM pressure scales with the contraction width (8 * (S*C+pad)
    # int8 rows of bits plus the int32 accumulator); shrink the lane
    # tile for wide matrices up front. F <= 32 keeps the full 64K
    # tile — measured FASTER there (k=32/F=32 at 64K ran 1.5x the
    # shrunken tile); only genuinely wide contractions shrink.
    f = s * c + pad
    if f > 32:
        while tile > LANE_TILE and tile > (65536 * 32) // f:
            tile //= 2
    if isinstance(data, jax.core.Tracer):
        # Under an outer trace the compile happens later, outside any
        # try here — no retry is possible, so go with the sized tile.
        return _apply_tiled(
            big, data, c, r, s, pad, tile, interpret=interpret
        )
    # Eager call: retry on compile failure rather than refusing
    # large k outright.
    while True:
        try:
            return _apply_tiled(
                big, data, c, r, s, pad, tile, interpret=interpret
            )
        except Exception:
            if tile <= LANE_TILE:
                raise
            tile //= 2
