"""Schedule-native XOR engine for sparse packet bit-matrix codes.

The reference executes liberation / blaum_roth / liber8tion (and the
cauchy techniques) as XOR *schedules*: ``jerasure_smart_bitmatrix_to_
schedule`` walks the 0/1 coding matrix and emits one XOR per set bit,
so encode cost tracks matrix density, not dimension
(jerasure/ErasureCodeJerasure.h:255-324, ``jerasure_schedule_encode``).
Routing those codes through the generic bit-plane MXU engine pays the
full [m*w*8, k*w*8] matrix stream with none of that sparsity — the r4
bench measured 35-83 GB/s vs 296 for the flagship byte code.

This module is the TPU form of the schedule: parity packet q is the
XOR of the data packets its matrix row selects (~k+1 of k*w for the
minimal-density families), executed as one Pallas VPU kernel blocked
over (stripe, lane-tile). No MXU, no bit-plane unpack — traffic is
(ones + m*w) packets per stripe against HBM, which on v5e measured
553-621 GB/s data-in at the r4 bench geometry (experiments/
exp_r5_sched.py), ~0.7x the pure-read roofline.

Dense matrices (inverted decode matrices run ~50% ones) stay on the
MXU engine — ``profitable`` gates the route by density.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

#: lane-tile granularity; multiples of 2048 keep uint8 blocks on the
#: native (32, 128) tiling, and 8192 measured at/above every larger
#: tile on v5e (grid-step overhead is already amortized there)
LANE_TILE = 2048
BEST_TILE = 8192

#: density gate: the schedule's HBM traffic is (ones + rows) packets
#: per ``cols`` packets of data in, so its rate is ~roofline/ratio.
#: The minimal-density families encode at ratio 2.1-3.0; the
#: single-chunk parity delta — the common small-write RMW shape —
#: runs 4 + 1/w (the fixed m*w output rows charge against one
#:  chunk's w columns), so the gate sits above that; inverted decode
#: matrices (~50% ones) run 10+ and stay on the MXU engine.
MAX_TRAFFIC_RATIO = 5.0


def schedule_rows(mat01: np.ndarray) -> tuple[tuple[int, ...], ...]:
    """Static XOR schedule: row q -> indices of the packets to XOR.

    The ``jerasure_smart_bitmatrix_to_schedule`` analog, except the
    "schedule" is consumed by a vector kernel instead of a C loop, so
    there is no operation reordering to minimize — only selection.
    """
    m = np.asarray(mat01)
    return tuple(
        tuple(int(j) for j in np.flatnonzero(m[q])) for q in range(m.shape[0])
    )


def profitable(
    sel_rows: tuple[tuple[int, ...], ...], cols: int
) -> bool:
    """True when the matrix is sparse enough that XOR traffic beats
    the MXU stream (minimal-density families: ~k+1 ones/row)."""
    if not sel_rows or cols <= 0:
        return False
    ones = sum(len(s) for s in sel_rows)
    return (ones + len(sel_rows)) <= MAX_TRAFFIC_RATIO * cols


def supported(shape: tuple[int, ...]) -> bool:
    """[B, KW, P] with the packet axis lane-tileable."""
    return len(shape) == 3 and shape[-1] % LANE_TILE == 0


def _pick_tile(p: int) -> int:
    if p % BEST_TILE == 0:
        return BEST_TILE
    t = BEST_TILE - LANE_TILE
    while t > LANE_TILE and p % t:
        t -= LANE_TILE
    return t


@functools.lru_cache(maxsize=256)
def _sched_fn(
    sel_rows: tuple[tuple[int, ...], ...],
    kw: int,
    lane_tile: int,
    interpret: bool,
):
    """Jitted (cached per static schedule) pallas apply. Functions only
    in this cache — never device arrays (the round-3/4 tracer-leak
    lesson applies to arrays, not callables)."""
    mw = len(sel_rows)

    def kernel(d_ref, o_ref):
        d = d_ref[:]  # [1, KW, T] uint8
        for q, sel in enumerate(sel_rows):
            if sel:
                acc = d[:, sel[0], :]
                for j in sel[1:]:
                    acc = acc ^ d[:, j, :]
            else:
                acc = jnp.zeros_like(d[:, 0, :])
            o_ref[:, q, :] = acc

    @jax.jit
    def apply(packets):
        b, _, p = packets.shape
        return pl.pallas_call(
            kernel,
            grid=(b, p // lane_tile),
            in_specs=[
                pl.BlockSpec((1, kw, lane_tile), lambda i, c: (i, 0, c))
            ],
            out_specs=pl.BlockSpec(
                (1, mw, lane_tile), lambda i, c: (i, 0, c)
            ),
            out_shape=jax.ShapeDtypeStruct((b, mw, p), jnp.uint8),
            interpret=interpret,
        )(packets)

    return apply


def _xla_apply(
    sel_rows: tuple[tuple[int, ...], ...], packets: jax.Array
) -> jax.Array:
    """Off-TPU form: unrolled jnp XOR chains (XLA fuses the row
    gathers and chains into one elementwise pass)."""
    outs = []
    zero = None
    for sel in sel_rows:
        if sel:
            acc = packets[..., sel[0], :]
            for j in sel[1:]:
                acc = acc ^ packets[..., j, :]
        else:
            if zero is None:
                zero = jnp.zeros_like(packets[..., 0, :])
            acc = zero
        outs.append(acc)
    return jnp.stack(outs, axis=-2)


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------- shards form
#: scoped VMEM is 16 MiB on v5e; Mosaic's own scratch for this kernel
#: measured ~3.8 MiB (a 12.58 MB block set OOMs by 396 KiB, an
#: 11.0 MB set compiles), so gate the whole-chunk form at 12 MB of
#: block bytes and leave the rest as headroom
VMEM_BUDGET = 12_000_000
SUBLANE = 8


def shards_supported(
    n_in: int, n_out: int, w: int, shape: tuple[int, ...]
) -> bool:
    """Can the shards-form kernel serve [B, chunk] shard arrays?

    Requirements: 2D after lead-flatten, packet size lane-aligned,
    batch a sublane multiple (or small enough to be one block), and
    (n_in + n_out) * sb * chunk within the VMEM budget.
    """
    if len(shape) < 1:
        return False
    chunk = shape[-1]
    b = int(np.prod(shape[:-1], initial=1))
    if chunk % w or (chunk // w) % 128:
        return False
    sb = SUBLANE if b % SUBLANE == 0 else b
    return (n_in + n_out) * sb * chunk <= VMEM_BUDGET


@functools.lru_cache(maxsize=256)
def _sched_shards_fn(
    sel_rows: tuple[tuple[int, ...], ...],
    n_in: int,
    w: int,
    chunk: int,
    sb: int,
    interpret: bool,
):
    """Multi-operand whole-chunk kernel: k separate [B, chunk] shard
    operands, m separate [B, chunk] parity results, packets addressed
    as in-kernel lane slices. The single-operand form pays a real
    relayout copy for the [B, k, chunk] stack and the packetize
    reshape (TPU tiles the minor-most two dims, so those reshapes
    move every byte); this form never materializes either — measured
    407 vs ~100 GB/s data-in on the r4 bench geometry
    (experiments/exp_r5_multiop.py)."""
    p = chunk // w
    n_out = len(sel_rows) // w

    def kernel(*refs):
        ins, outs = refs[:n_in], refs[n_in:]

        def packet(j):
            ci, pi = divmod(j, w)
            return ins[ci][:, pi * p : (pi + 1) * p]

        for q, sel in enumerate(sel_rows):
            if sel:
                acc = packet(sel[0])
                for j in sel[1:]:
                    acc = acc ^ packet(j)
            else:
                acc = jnp.zeros((refs[0].shape[0], p), jnp.uint8)
            qc, qp = divmod(q, w)
            outs[qc][:, qp * p : (qp + 1) * p] = acc

    @jax.jit
    def apply(*shards):
        b = shards[0].shape[0]
        return pl.pallas_call(
            kernel,
            grid=(b // sb,),
            in_specs=[
                pl.BlockSpec((sb, chunk), lambda i: (i, 0))
                for _ in range(n_in)
            ],
            out_specs=[
                pl.BlockSpec((sb, chunk), lambda i: (i, 0))
                for _ in range(n_out)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, chunk), jnp.uint8)
                for _ in range(n_out)
            ],
            interpret=interpret,
        )(*shards)

    return apply


def xor_schedule_apply_shards(
    sel_rows: tuple[tuple[int, ...], ...],
    shards: list,
    w: int,
    interpret: bool | None = None,
) -> list:
    """Shards-form schedule apply: ``shards`` are n_in arrays of
    [..., chunk] (common shape); returns n_out = len(sel_rows)/w
    arrays of the same shape, one per output shard. Row q of the
    schedule indexes input packet (q//w, q%w) across the shard list.

    On TPU this is the no-copy hot path; off-TPU it falls back to the
    fused-XLA packetized form (CPU tests can force interpret=True for
    bit-exact kernel coverage).
    """
    n_in = len(shards)
    lead = shards[0].shape[:-1]
    chunk = shards[0].shape[-1]
    n_out = len(sel_rows) // w
    if interpret is None:
        if not on_tpu():
            stacked = jnp.stack(
                [jnp.asarray(s) for s in shards], axis=-2
            )
            pk = stacked.reshape(lead + (n_in * w, chunk // w))
            out = _xla_apply(sel_rows, pk)
            ch = out.reshape(lead + (n_out, chunk))
            return [ch[..., j, :] for j in range(n_out)]
        interpret = False
    b = int(np.prod(lead, initial=1))
    sb = SUBLANE if b % SUBLANE == 0 else b
    fn = _sched_shards_fn(sel_rows, n_in, w, chunk, sb, interpret)
    flat = [jnp.asarray(s).reshape(b, chunk) for s in shards]
    outs = fn(*flat)
    return [o.reshape(lead + (chunk,)) for o in outs]


def xor_schedule_apply(
    sel_rows: tuple[tuple[int, ...], ...],
    packets: jax.Array,
    interpret: bool | None = None,
) -> jax.Array:
    """Apply a static XOR schedule to [..., KW, P] packets.

    Pallas kernel on TPU (or interpret=True for bit-exact CPU tests);
    plain fused XLA off-TPU. numpy input is accepted and returns a
    device array (callers on the host path use their own GF engine).
    """
    if interpret is None:
        interpret = False
        if not on_tpu():
            return _xla_apply(sel_rows, jnp.asarray(packets))
    lead = packets.shape[:-2]
    kw, p = packets.shape[-2:]
    if p % LANE_TILE:
        # a non-tileable packet axis would silently drop lanes (the
        # grid floors to zero/partial blocks); callers gate with
        # supported(), so reaching here is a contract violation
        raise ValueError(
            f"packet axis {p} not a multiple of {LANE_TILE}; "
            "check supported() before calling"
        )
    flat = jnp.asarray(packets).reshape((-1, kw, p))
    out = _sched_fn(sel_rows, kw, _pick_tile(p), interpret)(flat)
    return out.reshape(lead + out.shape[-2:])
