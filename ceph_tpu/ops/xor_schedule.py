"""Schedule-native XOR engine for sparse packet bit-matrix codes.

The reference executes liberation / blaum_roth / liber8tion (and the
cauchy techniques) as XOR *schedules*: ``jerasure_smart_bitmatrix_to_
schedule`` walks the 0/1 coding matrix and emits one XOR per set bit,
so encode cost tracks matrix density, not dimension
(jerasure/ErasureCodeJerasure.h:255-324, ``jerasure_schedule_encode``).
Routing those codes through the generic bit-plane MXU engine pays the
full [m*w*8, k*w*8] matrix stream with none of that sparsity — the r4
bench measured 35-83 GB/s vs 296 for the flagship byte code.

This module is the TPU form of the schedule — and, since round 11, a
schedule *optimizer* in the sense of "Accelerating XOR-based Erasure
Coding using Program Optimization Techniques" (arxiv 2108.02692):

- ``schedule_rows`` still emits the single-level selection form (row q
  = XOR of the packets its matrix row selects), the
  ``jerasure_smart_bitmatrix_to_schedule`` analog and the pinned
  bit-equal escape hatch (``ec_sched_opt=false``).
- ``optimize_schedule`` applies the paper's core move on top: greedy
  pairwise common-subexpression elimination over the 0/1 matrix
  (Paar's algorithm) factors XOR pairs shared across parity rows into
  intermediate packets, recursively (intermediates pair with
  intermediates, so schedules are multi-level), then ``_linearize``
  reorders the resulting DAG for VMEM/operand locality: outputs chain
  by operand affinity, intermediates materialize just before first
  use into scratch slots that are recycled at last use (register-
  allocation over VMEM), bounding live intermediates to the DAG's
  peak width instead of its size.

Both Pallas kernels (the packetized form and the multi-operand shards
form) execute the linearized program with intermediates staged in a
VMEM scratch ref; XOR is exact on uint8, so any operand order is
bit-equal to the un-optimized schedule and to the host GF engine.

Execution model: parity packet q is the XOR of the data packets (and
intermediates) its program selects, executed as one Pallas VPU kernel
blocked over (stripe, lane-tile). No MXU, no bit-plane unpack — the
blocks stream (cols + rows) packets per stripe against HBM, which on
v5e measured 553-621 GB/s data-in at the r4 bench geometry
(experiments/exp_r5_sched.py), ~0.7x the pure-read roofline, while
the VPU work per block tracks the schedule's op count.

Gate math (round 11): the un-optimized route keeps the original
traffic-ratio gate — (ones + rows) <= MAX_TRAFFIC_RATIO * cols, the
r4/r5 model where every set bit is one operand read. The optimized
route gates on the *post-CSE op count* instead: (XORs + output
writes) <= MAX_OP_RATIO * cols. Minimal-density encode matrices pass
both (ratio 2.0-2.2 post-CSE); inverted decode matrices (~50% ones,
raw ratio 7-8, rejected by the old gate) compress under CSE to ratio
~2.5 and now ride the schedule route, as do LRC xor-local-parity
repair rows — the r11 superopt targets (experiments/
exp_r11_sched_superopt.py).
"""

from __future__ import annotations

import functools
import heapq
from collections import Counter
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: lane-tile granularity; multiples of 2048 keep uint8 blocks on the
#: native (32, 128) tiling, and 8192 measured at/above every larger
#: tile on v5e (grid-step overhead is already amortized there)
LANE_TILE = 2048
BEST_TILE = 8192
#: the divisor-search fallback in ``_pick_tile`` stays lane-aligned
#: (multiples of 128) and above a floor so awkward packet sizes never
#: degrade to sliver tiles whose grid-step overhead dominates
TILE_ALIGN = 128
MIN_TILE = 512

#: density gate for the UN-optimized (selection-form) schedule — the
#: ``ec_sched_opt=false`` escape hatch: HBM traffic is (ones + rows)
#: packets per ``cols`` packets of data in, so its rate is
#: ~roofline/ratio. The minimal-density families encode at ratio
#: 2.1-3.0; the single-chunk parity delta — the common small-write
#: RMW shape — runs 4 + 1/w (the fixed m*w output rows charge
#: against one chunk's w columns), so the gate sits above that;
#: inverted decode matrices (~50% ones) run 7-10 raw and only pass
#: through the optimized gate below.
MAX_TRAFFIC_RATIO = 5.0

#: op-count gate for OPTIMIZED schedules: (post-CSE XORs + output
#: writes) per data column. Same constant as the traffic gate — the
#: MXU-stream comparator is unchanged — but measured after CSE, which
#: is what converts the ~50%-ones inverted decode matrices (raw 7-8)
#: into ratio ~2.5 programs that beat the matrix stream.
MAX_OP_RATIO = 5.0


class Schedule(NamedTuple):
    """A multi-level XOR program over packet node ids.

    Nodes 0..n_in-1 are the input packets; node n_in + t is
    intermediate ``temps[t]``, defined as the XOR of two earlier
    nodes (inputs or intermediates — CSE pairs recursively). Output
    row q is the XOR of ``outputs[q]``'s nodes; an empty tuple means
    a zero packet. Hashable, so it keys the jitted-kernel caches the
    same way the plain selection rows do.
    """

    n_in: int
    temps: tuple[tuple[int, int], ...]
    outputs: tuple[tuple[int, ...], ...]


def schedule_rows(mat01: np.ndarray) -> tuple[tuple[int, ...], ...]:
    """Single-level XOR schedule: row q -> indices of the packets to
    XOR — the ``jerasure_smart_bitmatrix_to_schedule`` analog, pure
    selection with no factoring. This form is kept verbatim as the
    ``ec_sched_opt=false`` escape hatch (pinned bit-equal, and pinned
    *structurally*: the kernels run it through the original
    single-level code path); ``optimize_schedule`` builds the CSE'd
    multi-level program the optimizer route dispatches.
    """
    m = np.asarray(mat01)
    return tuple(
        tuple(int(j) for j in np.flatnonzero(m[q])) for q in range(m.shape[0])
    )


def optimize_schedule(mat01: np.ndarray) -> Schedule:
    """Greedy pairwise CSE over a 0/1 matrix (Paar's algorithm).

    Repeatedly factor the operand pair co-occurring in the most rows
    into a fresh intermediate (each factoring saves >= 1 XOR: one
    intermediate XOR buys >= 2 pair eliminations), substituting the
    intermediate everywhere — including into pairs with other
    intermediates, so the result is multi-level. Deterministic:
    ties break to the lexicographically smallest pair, so golden
    op-count pins (tests/test_sched_superopt.py) hold across runs.

    Pair counts update incrementally with a lazy max-heap — O(E log E)
    in the number of count updates — so dense inverted decode
    matrices optimize in milliseconds, not the seconds a recount-
    per-iteration scan costs.
    """
    m = np.asarray(mat01, dtype=np.uint8)
    n_out, n_in = m.shape
    rows = [set(int(j) for j in np.flatnonzero(m[q])) for q in range(n_out)]
    cnt: Counter = Counter()
    for r in rows:
        s = sorted(r)
        for i in range(len(s)):
            for j in range(i + 1, len(s)):
                cnt[(s[i], s[j])] += 1
    heap = [(-c, p) for p, c in cnt.items()]
    heapq.heapify(heap)
    temps: list[tuple[int, int]] = []
    next_id = n_in

    def bump(pair: tuple[int, int], d: int) -> None:
        c = cnt[pair] + d
        if c <= 0:
            cnt.pop(pair, None)
        else:
            cnt[pair] = c
            heapq.heappush(heap, (-c, pair))

    while heap:
        negc, pair = heapq.heappop(heap)
        if cnt.get(pair, 0) != -negc:
            continue  # stale heap entry (lazy deletion)
        if -negc < 2:
            break
        a, b = pair
        tid = next_id
        next_id += 1
        temps.append((a, b))
        hits = 0
        for r in rows:
            if a in r and b in r:
                hits += 1
                r.discard(a)
                r.discard(b)
                for x in r:
                    bump((x, a) if x < a else (a, x), -1)
                    bump((x, b) if x < b else (b, x), -1)
                    bump((x, tid), +1)  # tid > every existing node
                r.add(tid)
        bump(pair, -hits)
    return Schedule(
        n_in,
        tuple(temps),
        tuple(tuple(sorted(r)) for r in rows),
    )


def schedule_xors(sel) -> int:
    """XOR ops a schedule executes (either form): intermediate XORs
    plus per-row chain XORs. The quantity the optimized gate and the
    bench/CI op-count pins measure."""
    if isinstance(sel, Schedule):
        return len(sel.temps) + sum(
            max(len(o) - 1, 0) for o in sel.outputs
        )
    return sum(max(len(s) - 1, 0) for s in sel)


def cse_stats(mat01: np.ndarray) -> dict:
    """Optimizer scorecard for one matrix: raw ones / selection-form
    XORs / post-CSE XORs / intermediate count / scratch-slot peak.
    Consumed by bench.py's sched-superopt phase and the golden
    op-count regression pins."""
    m = np.asarray(mat01, dtype=np.uint8)
    rows = schedule_rows(m)
    sched = optimize_schedule(m)
    raw = schedule_xors(rows)
    opt = schedule_xors(sched)
    return {
        "ones": int(m.sum()),
        "raw_xors": raw,
        "opt_xors": opt,
        "temps": len(sched.temps),
        "saving_frac": round(1.0 - opt / max(raw, 1), 3),
        "scratch_slots": _linearize(sched)[1],
    }


def profitable(
    sel_rows: tuple[tuple[int, ...], ...], cols: int
) -> bool:
    """Selection-form gate (the escape-hatch route): True when the
    matrix is sparse enough that raw XOR traffic beats the MXU stream
    (minimal-density families: ~k+1 ones/row). See MAX_TRAFFIC_RATIO
    for the model; optimized schedules gate via ``profitable_opt``."""
    if not sel_rows or cols <= 0:
        return False
    ones = sum(len(s) for s in sel_rows)
    return (ones + len(sel_rows)) <= MAX_TRAFFIC_RATIO * cols


def profitable_opt(sched: Schedule, cols: int) -> bool:
    """Optimizer-route gate: post-CSE op count (XORs + output writes)
    per data column against the same MXU-stream comparator. This is
    what lets CSE-compressible dense shapes — inverted decode
    matrices, LRC local-repair rows — ride the schedule route the
    raw-density gate locked out."""
    if not sched.outputs or cols <= 0:
        return False
    return (schedule_xors(sched) + len(sched.outputs)) <= (
        MAX_OP_RATIO * cols
    )


@functools.lru_cache(maxsize=1024)
def _routable_cached(mat_bytes: bytes, shape: tuple, opt: bool):
    m = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(shape)
    if opt:
        sched = optimize_schedule(m)
        return sched if profitable_opt(sched, shape[1]) else None
    rows = schedule_rows(m)
    return rows if profitable(rows, shape[1]) else None


def routable_schedule(mat01: np.ndarray, opt: bool = True):
    """The schedule the route should execute for a 0/1 matrix, or
    None when even the post-CSE program stays over the gate (the
    matrix is served better by the MXU stream). ``opt=False`` is the
    ``ec_sched_opt`` escape hatch: the raw selection form under the
    original traffic-ratio gate. Cached process-wide — schedules
    depend only on the matrix, so every codec shares one table."""
    m = np.ascontiguousarray(np.asarray(mat01, dtype=np.uint8))
    return _routable_cached(m.tobytes(), m.shape, bool(opt))


def supported(shape: tuple[int, ...]) -> bool:
    """[B, KW, P] with the packet axis lane-tileable."""
    return len(shape) == 3 and shape[-1] % LANE_TILE == 0


def _pick_tile(p: int) -> int:
    """Largest grid-remainder-free lane tile for a packet axis of
    ``p`` lanes: BEST_TILE when it divides exactly, else the largest
    divisor of p that is lane-aligned (multiple of TILE_ALIGN) at or
    under BEST_TILE with a MIN_TILE floor. The old search only walked
    LANE_TILE multiples, so awkward packet sizes (p with no large
    2048-multiple divisor, e.g. 10240 or 14336) degraded to a 2048
    sliver and paid 4-7x the grid steps; the divisor search keeps
    them at 5120/7168."""
    if p % BEST_TILE == 0:
        return BEST_TILE
    best = 0
    t = TILE_ALIGN
    while t <= BEST_TILE and t <= p:
        if t >= MIN_TILE and p % t == 0:
            best = t
        t += TILE_ALIGN
    if best:
        return best
    # no aligned divisor at/above the floor — legacy LANE_TILE-step
    # fallback (unreachable while supported() demands p % 2048 == 0,
    # kept for forward safety if the alignment contract relaxes)
    t = BEST_TILE - LANE_TILE
    while t > LANE_TILE and p % t:
        t -= LANE_TILE
    return t


# ------------------------------------------------------ linearization
@functools.lru_cache(maxsize=512)
def _linearize(sched: Schedule):
    """Compile a Schedule into ``(ops, n_slots)`` — the VMEM-local
    execution order both kernels run.

    - Output rows chain greedily by operand affinity (next row shares
      the most operands with the previous one), so consecutive rows
      re-read hot operands.
    - Intermediates materialize lazily, immediately before their
      first use (dependencies first — creation order is already
      topological), and their scratch slot is recycled at last use:
      ``n_slots`` is the DAG's peak liveness, not its size, which is
      what the shards form charges against the VMEM budget.
    - Within a row, intermediate operands lead (most recent first —
      the hottest VMEM lines) and input packets follow in index
      order. XOR on uint8 is exact, so every ordering is bit-equal.

    ``ops`` entries: ``("t", slot, (src, src))`` materializes an
    intermediate, ``("o", q, (src, ...))`` emits output row q; each
    ``src`` is ``(0, input_index)`` or ``(1, slot)``.
    """
    n_in, temps, outputs = sched.n_in, sched.temps, sched.outputs
    remaining = list(range(len(outputs)))
    order: list[int] = []
    prev: set[int] = set()
    while remaining:
        q = max(
            remaining,
            key=lambda r: (len(prev & set(outputs[r])), -r),
        )
        order.append(q)
        remaining.remove(q)
        prev = set(outputs[q])

    seq: list[tuple[str, int]] = []
    emitted: set[int] = set()

    def emit(t: int) -> None:
        if t in emitted:
            return
        emitted.add(t)
        for d in temps[t]:
            if d >= n_in:
                emit(d - n_in)
        seq.append(("t", t))

    for q in order:
        for x in outputs[q]:
            if x >= n_in:
                emit(x - n_in)
        seq.append(("o", q))

    last_use: dict[int, int] = {}
    for i, (kind, x) in enumerate(seq):
        for r in temps[x] if kind == "t" else outputs[x]:
            if r >= n_in:
                last_use[r - n_in] = i

    slot_of: dict[int, int] = {}
    free: list[int] = []
    n_slots = 0
    ops: list[tuple] = []

    def src(v: int) -> tuple[int, int]:
        return (0, v) if v < n_in else (1, slot_of[v - n_in])

    for i, (kind, x) in enumerate(seq):
        if kind == "t":
            a, b = temps[x]
            srcs = (src(a), src(b))
            # destination allocated BEFORE operand slots release, so
            # a temp never aliases its own operands' storage
            s = free.pop() if free else n_slots
            n_slots = max(n_slots, s + 1)
            slot_of[x] = s
            ops.append(("t", s, srcs))
        else:
            ids = outputs[x]
            ts = sorted((v for v in ids if v >= n_in), reverse=True)
            ins_ = sorted(v for v in ids if v < n_in)
            ops.append(("o", x, tuple(src(v) for v in ts + ins_)))
        for r in temps[x] if kind == "t" else outputs[x]:
            if r >= n_in and last_use.get(r - n_in) == i:
                free.append(slot_of[r - n_in])
    return tuple(ops), n_slots


# ------------------------------------------------------------- kernels
@functools.lru_cache(maxsize=256)
def _sched_fn(
    sel_rows,
    kw: int,
    lane_tile: int,
    interpret: bool,
):
    """Jitted (cached per static schedule) pallas apply. Functions only
    in this cache — never device arrays (the round-3/4 tracer-leak
    lesson applies to arrays, not callables). Plain selection rows run
    the original single-level kernel unchanged; ``Schedule`` programs
    run their linearized op list with intermediates staged in a VMEM
    scratch ref (one lane-tile row per live slot)."""
    scratch_shapes: list = []
    if isinstance(sel_rows, Schedule):
        ops, n_slots = _linearize(sel_rows)
        mw = len(sel_rows.outputs)
        if n_slots:
            scratch_shapes = [
                pltpu.VMEM((n_slots, lane_tile), jnp.uint8)
            ]

        def kernel(d_ref, o_ref, *scratch):
            d = d_ref[:]  # [1, KW, T] uint8
            scr = scratch[0] if scratch else None

            def val(s):
                kind, i = s
                if kind == 0:
                    return d[:, i, :]
                return scr[i : i + 1, :]

            for entry in ops:
                if entry[0] == "t":
                    _, slot, (a, b) = entry
                    scr[slot : slot + 1, :] = val(a) ^ val(b)
                else:
                    _, q, srcs = entry
                    if srcs:
                        acc = val(srcs[0])
                        for s in srcs[1:]:
                            acc = acc ^ val(s)
                    else:
                        acc = jnp.zeros_like(d[:, 0, :])
                    o_ref[:, q, :] = acc

    else:
        mw = len(sel_rows)

        def kernel(d_ref, o_ref):
            d = d_ref[:]  # [1, KW, T] uint8
            for q, sel in enumerate(sel_rows):
                if sel:
                    acc = d[:, sel[0], :]
                    for j in sel[1:]:
                        acc = acc ^ d[:, j, :]
                else:
                    acc = jnp.zeros_like(d[:, 0, :])
                o_ref[:, q, :] = acc

    @jax.jit
    def apply(packets):
        b, _, p = packets.shape
        return pl.pallas_call(
            kernel,
            grid=(b, p // lane_tile),
            in_specs=[
                pl.BlockSpec((1, kw, lane_tile), lambda i, c: (i, 0, c))
            ],
            out_specs=pl.BlockSpec(
                (1, mw, lane_tile), lambda i, c: (i, 0, c)
            ),
            out_shape=jax.ShapeDtypeStruct((b, mw, p), jnp.uint8),
            scratch_shapes=scratch_shapes,
            interpret=interpret,
        )(packets)

    return apply


def _xla_apply(sel_rows, packets: jax.Array) -> jax.Array:
    """Off-TPU form: unrolled jnp XOR chains (XLA fuses the row
    gathers and chains into one elementwise pass). Multi-level
    schedules compute their intermediates as ordinary fused values."""
    if isinstance(sel_rows, Schedule):
        n_in = sel_rows.n_in
        vals: dict[int, jax.Array] = {}

        def node(i):
            return packets[..., i, :] if i < n_in else vals[i]

        for t, (a, b) in enumerate(sel_rows.temps):
            vals[n_in + t] = node(a) ^ node(b)
        rows = sel_rows.outputs
        fetch = node
    else:
        rows = sel_rows
        fetch = lambda j: packets[..., j, :]  # noqa: E731
    outs = []
    zero = None
    for sel in rows:
        if sel:
            acc = fetch(sel[0])
            for j in sel[1:]:
                acc = acc ^ fetch(j)
        else:
            if zero is None:
                zero = jnp.zeros_like(packets[..., 0, :])
            acc = zero
        outs.append(acc)
    return jnp.stack(outs, axis=-2)


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _n_rows(sel) -> int:
    """Output-row count of either schedule form."""
    return len(sel.outputs) if isinstance(sel, Schedule) else len(sel)


# ---------------------------------------------------------- shards form
#: scoped VMEM is 16 MiB on v5e; Mosaic's own scratch for this kernel
#: measured ~3.8 MiB (a 12.58 MB block set OOMs by 396 KiB, an
#: 11.0 MB set compiles), so gate the whole-chunk form at 12 MB of
#: block bytes and leave the rest as headroom
VMEM_BUDGET = 12_000_000
SUBLANE = 8


def shards_supported(
    n_in: int,
    n_out: int,
    w: int,
    shape: tuple[int, ...],
    n_slots: int = 0,
) -> bool:
    """Can the shards-form kernel serve [B, chunk] shard arrays?

    Requirements: 2D after lead-flatten, packet size lane-aligned,
    batch a sublane multiple (or small enough to be one block), and
    (n_in + n_out) * sb * chunk — plus the optimizer's scratch,
    ``n_slots`` live intermediate packets of sb * (chunk/w) bytes —
    within the VMEM budget.
    """
    if len(shape) < 1:
        return False
    chunk = shape[-1]
    b = int(np.prod(shape[:-1], initial=1))
    if chunk % w or (chunk // w) % 128:
        return False
    sb = SUBLANE if b % SUBLANE == 0 else b
    blocks = (n_in + n_out) * sb * chunk + n_slots * sb * (chunk // w)
    return blocks <= VMEM_BUDGET


@functools.lru_cache(maxsize=256)
def _sched_shards_fn(
    sel_rows,
    n_in: int,
    w: int,
    chunk: int,
    sb: int,
    interpret: bool,
):
    """Multi-operand whole-chunk kernel: k separate [B, chunk] shard
    operands, m separate [B, chunk] parity results, packets addressed
    as in-kernel lane slices. The single-operand form pays a real
    relayout copy for the [B, k, chunk] stack and the packetize
    reshape (TPU tiles the minor-most two dims, so those reshapes
    move every byte); this form never materializes either — measured
    407 vs ~100 GB/s data-in on the r4 bench geometry
    (experiments/exp_r5_multiop.py). ``Schedule`` programs execute
    their linearized op list with intermediates in a VMEM scratch ref
    (sb rows per live slot, recycled at last use)."""
    p = chunk // w
    scratch_shapes: list = []
    if isinstance(sel_rows, Schedule):
        ops, n_slots = _linearize(sel_rows)
        n_out = len(sel_rows.outputs) // w
        if n_slots:
            scratch_shapes = [
                pltpu.VMEM((n_slots * sb, p), jnp.uint8)
            ]

        def kernel(*refs):
            ins = refs[:n_in]
            outs = refs[n_in : n_in + n_out]
            scr = refs[n_in + n_out] if n_slots else None

            def val(s):
                kind, i = s
                if kind == 0:
                    ci, pi = divmod(i, w)
                    return ins[ci][:, pi * p : (pi + 1) * p]
                return scr[i * sb : (i + 1) * sb, :]

            for entry in ops:
                if entry[0] == "t":
                    _, slot, (a, b) = entry
                    scr[slot * sb : (slot + 1) * sb, :] = (
                        val(a) ^ val(b)
                    )
                else:
                    _, q, srcs = entry
                    if srcs:
                        acc = val(srcs[0])
                        for s in srcs[1:]:
                            acc = acc ^ val(s)
                    else:
                        acc = jnp.zeros(
                            (refs[0].shape[0], p), jnp.uint8
                        )
                    qc, qp = divmod(q, w)
                    outs[qc][:, qp * p : (qp + 1) * p] = acc

    else:
        n_out = len(sel_rows) // w

        def kernel(*refs):
            ins, outs = refs[:n_in], refs[n_in:]

            def packet(j):
                ci, pi = divmod(j, w)
                return ins[ci][:, pi * p : (pi + 1) * p]

            for q, sel in enumerate(sel_rows):
                if sel:
                    acc = packet(sel[0])
                    for j in sel[1:]:
                        acc = acc ^ packet(j)
                else:
                    acc = jnp.zeros((refs[0].shape[0], p), jnp.uint8)
                qc, qp = divmod(q, w)
                outs[qc][:, qp * p : (qp + 1) * p] = acc

    @jax.jit
    def apply(*shards):
        b = shards[0].shape[0]
        return pl.pallas_call(
            kernel,
            grid=(b // sb,),
            in_specs=[
                pl.BlockSpec((sb, chunk), lambda i: (i, 0))
                for _ in range(n_in)
            ],
            out_specs=[
                pl.BlockSpec((sb, chunk), lambda i: (i, 0))
                for _ in range(n_out)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, chunk), jnp.uint8)
                for _ in range(n_out)
            ],
            scratch_shapes=scratch_shapes,
            interpret=interpret,
        )(*shards)

    return apply


def xor_schedule_apply_shards(
    sel_rows,
    shards: list,
    w: int,
    interpret: bool | None = None,
) -> list:
    """Shards-form schedule apply: ``shards`` are n_in arrays of
    [..., chunk] (common shape); returns n_out = rows/w arrays of the
    same shape, one per output shard. Row q of the schedule indexes
    input packet (q//w, q%w) across the shard list; ``sel_rows`` is
    either the selection form or an optimized ``Schedule``. ``w=1``
    serves whole-chunk 0/1 byte matrices (LRC xor-local-parity
    repair), where packet == chunk.

    On TPU this is the no-copy hot path; off-TPU it falls back to the
    fused-XLA packetized form (CPU tests can force interpret=True for
    bit-exact kernel coverage).
    """
    n_in = len(shards)
    lead = shards[0].shape[:-1]
    chunk = shards[0].shape[-1]
    n_out = _n_rows(sel_rows) // w
    if interpret is None:
        if not on_tpu():
            stacked = jnp.stack(
                [jnp.asarray(s) for s in shards], axis=-2
            )
            pk = stacked.reshape(lead + (n_in * w, chunk // w))
            out = _xla_apply(sel_rows, pk)
            ch = out.reshape(lead + (n_out, chunk))
            return [ch[..., j, :] for j in range(n_out)]
        interpret = False
    b = int(np.prod(lead, initial=1))
    sb = SUBLANE if b % SUBLANE == 0 else b
    fn = _sched_shards_fn(sel_rows, n_in, w, chunk, sb, interpret)
    flat = [jnp.asarray(s).reshape(b, chunk) for s in shards]
    outs = fn(*flat)
    return [o.reshape(lead + (chunk,)) for o in outs]


def xor_schedule_apply(
    sel_rows,
    packets: jax.Array,
    interpret: bool | None = None,
) -> jax.Array:
    """Apply a static XOR schedule (either form) to [..., KW, P]
    packets.

    Pallas kernel on TPU (or interpret=True for bit-exact CPU tests);
    plain fused XLA off-TPU. numpy input is accepted and returns a
    device array (callers on the host path use their own GF engine).
    """
    if interpret is None:
        interpret = False
        if not on_tpu():
            return _xla_apply(sel_rows, jnp.asarray(packets))
    lead = packets.shape[:-2]
    kw, p = packets.shape[-2:]
    if p % LANE_TILE:
        # a non-tileable packet axis would silently drop lanes (the
        # grid floors to zero/partial blocks); callers gate with
        # supported(), so reaching here is a contract violation
        raise ValueError(
            f"packet axis {p} not a multiple of {LANE_TILE}; "
            "check supported() before calling"
        )
    flat = jnp.asarray(packets).reshape((-1, kw, p))
    out = _sched_fn(sel_rows, kw, _pick_tile(p), interpret)(flat)
    return out.reshape(lead + out.shape[-2:])
