"""Pallas kernels for CLAY fractional repair — general d, any chunk.

The XLA formulation of the repair stages (stack rows -> plane-permute
gather -> fused pair transform; pair-combine -> stack -> inverse
permute) pays every intermediate against HBM: ~500 MB of traffic to
repair 45 MB of helper bytes.  These kernels express the SAME algebra
as lane-sliced networks: every (node, plane-digit) class of a helper
row is one 2D ``(sb, lb)`` lane block whose companion block is another
ref of the same pallas_call, so each pair transform is a handful of
packed-int32 VPU ops and HBM sees each helper byte once in, each
recovered byte once out.

v2 design (round 9), replacing the aloof-free whole-chunk kernels:

- **General d.**  Repair with ``k <= d < k+m-1`` leaves ``k+m-1-d``
  helper nodes "aloof".  Aloof nodes contribute no helper bytes; their
  uncoupled values come out of the per-score-group inner-MDS decodes
  and re-enter the NEXT group's solve as known rows — the B1/B2
  helper split of ``repair_one_lost_chunk`` (ErasureCodeClay.cc:
  454-699).  The kernel computes every pair transform that does not
  depend on an aloof U (B1) and emits the helper's own coupled value
  as a placeholder for the few that do (B2); the codec patches those
  between group decodes (codecs/clay.py) — they are a 1/q fraction of
  one row per aloof node, far too small to earn a kernel.
- **Plane-blocked streaming.**  The round-7 kernels held the WHOLE
  output chunk per grid step, capping ``sub_chunk_no * sc`` at the
  1 Mi-lane VMEM scatter budget (a 1 MiB-chunk (8,4,d=11) repair —
  the flagship geometry — already overflowed it).  Now every ref is a
  2D ``(sb, lb)`` lane block with ``lb | sc``; the grid walks the
  repair-plane lane space and the per-class index maps do the digit
  arithmetic, so VMEM per step is ``refs * sb * lb`` bytes no matter
  how large ``sub_chunk_no * sc`` grows.  ``supported()`` therefore
  carries NO chunk-size cap any more — only lane alignment and a ref
  budget.
- **Any pair algebra.**  Coefficients are static Python ints baked
  into the kernel as shift/mask peasant ladders on packed int32 lanes
  (Mosaic cannot shift i8 vectors); the canonical RS(2,2) coupling
  reduces to the one-step ``U = C ^ 2*(C_hi^C_lo)`` /
  ``C = C_x ^ inv2*(C_x^U_x)`` fusions, anything else takes the
  general ladder.  The old ``_canonical_pair_algebra`` routing gate
  is gone.

Geometry conventions (see codecs/clay.py): nodes live on a q x t
grid; the lost node is (x_l, y_l); repair planes are the sub-chunks
whose digit y_l equals x_l, indexed 0..r-1 in ascending plane order
(r = sub_chunk_no / q).  Changing digit ``y`` of a repair plane by
``delta`` moves its repair index by ``delta * stride(y)`` where
``stride(y) = q ** #{y' > y, y' != y_l}`` — all static, which is what
lets DMA index maps do every gather and scatter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .pallas_encode import bitcast_i32_to_u8, bitcast_u8_to_i32

SB = 8   # minimum stripes per block (sublane granularity)
#: per-grid-step VMEM budget in bytes across all refs: blocks are
#: (sb, lb) u8 lanes; lb shrinks (halving, floor 128) until the step
#: fits.  4 MiB leaves headroom beside the double-buffered pipeline.
STEP_BYTES = 4 << 20
#: ref-count cap: (t-1)*q*(q+1) in+out refs for the uncoupled kernel
#: (each of the (t-1)*q helper rows is read once per companion digit
#: class).  Mosaic compiles ~64 refs comfortably; wider geometries
#: fall back to the XLA paths.
MAX_REFS = 64


def supported(b: int, sc: int, q: int, t: int) -> bool:
    """Kernel preconditions: batch blocks on sublanes, plane packets
    lane-align, and the ref fan-out stays within the Mosaic budget.
    Unlike the round-7 kernels there is NO ``sub_chunk_no * sc`` cap:
    blocks are fixed-size lane slices, so any chunk size streams."""
    return (
        b % SB == 0
        and sc % 128 == 0
        and q >= 2
        and t >= 2
        and (t - 1) * q * (q + 1) <= MAX_REFS
    )


def _pick_sb(b: int) -> int:
    """16 measured ~1 GB/s over 8 on the round-7 kernels (fewer DMA
    grid steps); fall back to the sublane minimum otherwise."""
    return 16 if b % 16 == 0 else SB


def _pick_lb(sc: int, n_refs: int, sb: int) -> int:
    """Largest lane-block dividing ``sc`` that keeps one grid step's
    resident refs within STEP_BYTES (halving preserves divisibility;
    128 always divides sc per ``supported``)."""
    lb = sc
    while lb >= 256 and lb % 2 == 0 and n_refs * sb * lb > STEP_BYTES:
        lb //= 2
    if lb % 128 or n_refs * sb * lb > STEP_BYTES:
        lb = 128
    return lb


# ------------------------------------------------------- packed GF ops
def _mul2_i32(xi):
    """Per-byte GF(2^8)/0x11D multiply-by-2 on packed int32 lanes."""
    return (
        ((xi & jnp.int32(0x7F7F7F7F)) << jnp.int32(1))
        ^ (((xi >> jnp.int32(7)) & jnp.int32(0x01010101))
           * jnp.int32(0x1D))
    )


def _div2_i32(xi):
    """Per-byte multiply by inv(2) = 142 on packed int32 lanes."""
    return (
        ((xi >> jnp.int32(1)) & jnp.int32(0x7F7F7F7F))
        ^ ((xi & jnp.int32(0x01010101)) * jnp.int32(0x8E))
    )


def _mulc_i32(xi, c: int):
    """Per-byte GF(2^8) multiply by the static constant ``c`` — the
    shift/mask peasant ladder, bit-length many _mul2 steps."""
    if c == 0:
        return jnp.zeros_like(xi)
    acc = None
    cur = xi
    cc = c
    while cc:
        if cc & 1:
            acc = cur if acc is None else acc ^ cur
        cc >>= 1
        if cc:
            cur = _mul2_i32(cur)
    return acc


def _pair_i32(a, b, c0: int, c1: int):
    """``c0*a ^ c1*b`` with the canonical coupling coefficients fused
    to single mul2/div2 steps.  ``a``/``b`` may be None (a statically
    zero operand — shortened virtual nodes)."""
    if a is None and b is None:
        return None  # two virtual (zero) nodes pair to zero
    if a is None:
        return _mulc_i32(b, c1)
    if b is None:
        return _mulc_i32(a, c0)
    if (c0, c1) == (1, 0):
        return a
    if (c0, c1) == (0, 1):
        return b
    if (c0, c1) == (3, 2):
        return a ^ _mul2_i32(a ^ b)
    if (c0, c1) == (2, 3):
        return b ^ _mul2_i32(a ^ b)
    if (c0, c1) == (143, 142):
        return a ^ _div2_i32(a ^ b)
    if (c0, c1) == (142, 143):
        return b ^ _div2_i32(a ^ b)
    return _mulc_i32(a, c0) ^ _mulc_i32(b, c1)


# -------------------------------------------------- uncoupled solve (a)
@functools.lru_cache(maxsize=64)
def _uncoupled_fn(
    q: int,
    strides: tuple[int, ...],
    kinds: tuple[tuple[str, ...], ...],
    pair_fwd: tuple[tuple[int, int], tuple[int, int]],
    r: int,
    sc: int,
    sb: int,
    interpret: bool,
):
    """Stage-a kernel builder.  One ref per (row, real member, digit
    class) — q index-mapped views of each helper array — and one
    ``[B, Mj, q, stride*sc]`` output per non-aloof member, so every
    pair transform finds both operands resident without a gather.

    ``strides[ri]`` is row ri's repair-index digit stride; ``kinds``
    marks members 'r'eal / 'v'irtual (shortened, statically zero) /
    'a'loof (no bytes; B2 classes emit the helper's C as the patch
    placeholder); ``pair_fwd`` the (self, partner) coefficients for
    the hi/lo pair member."""
    n_rows = len(kinds)
    in_plan: list[tuple[int, int, int]] = []   # (row, x, zv)
    in_idx: dict[tuple[int, int, int], int] = {}
    out_plan: list[tuple[int, int]] = []       # (row, x)
    for ri in range(n_rows):
        for x in range(q):
            if kinds[ri][x] == "r":
                for zv in range(q):
                    in_idx[(ri, x, zv)] = len(in_plan)
                    in_plan.append((ri, x, zv))
            if kinds[ri][x] != "a":
                out_plan.append((ri, x))
    n_in = len(in_plan)
    lb = _pick_lb(sc, n_in + len(out_plan) * q, sb)

    def kernel(*refs):
        ins, outs = refs[:n_in], refs[n_in:]
        cache: dict[tuple[int, int, int], jax.Array] = {}

        def block(ri, x, zv):
            key = (ri, x, zv)
            if key not in cache:
                cache[key] = bitcast_u8_to_i32(
                    ins[in_idx[key]][:], interpret
                )
            return cache[key]

        for oi, (ri, x) in enumerate(out_plan):
            for zv in range(q):
                if zv == x:
                    # dot plane: U = C (virtual: U = 0)
                    if kinds[ri][x] == "v":
                        outs[oi][:, 0, zv, :] = jnp.zeros(
                            (sb, lb), jnp.uint8
                        )
                        continue
                    u = block(ri, x, zv)
                elif kinds[ri][x] == "r" and kinds[ri][zv] == "a":
                    # B2 class: companion U is decoded later — emit C
                    # as the placeholder the codec's patch consumes.
                    u = block(ri, x, zv)
                else:
                    a = (
                        block(ri, x, zv)
                        if kinds[ri][x] == "r" else None
                    )
                    bb = (
                        block(ri, zv, x)
                        if kinds[ri][zv] == "r" else None
                    )
                    c0, c1 = pair_fwd[0] if x > zv else pair_fwd[1]
                    u = _pair_i32(a, bb, c0, c1)
                    if u is None:  # virtual pair: statically zero
                        outs[oi][:, 0, zv, :] = jnp.zeros(
                            (sb, lb), jnp.uint8
                        )
                        continue
                outs[oi][:, 0, zv, :] = bitcast_i32_to_u8(u, interpret)

    @jax.jit
    def apply(*helpers):
        b = helpers[0].shape[0]
        in_specs = []
        operands = []
        helpers_by_rx = {}
        hi = 0
        for ri in range(n_rows):
            for x in range(q):
                if kinds[ri][x] == "r":
                    helpers_by_rx[(ri, x)] = helpers[hi]
                    hi += 1
        for ri, x, zv in in_plan:
            s = strides[ri]
            spb = s * sc // lb
            operands.append(helpers_by_rx[(ri, x)])
            in_specs.append(pl.BlockSpec(
                (sb, lb),
                lambda bi, w, zv=zv, spb=spb: (
                    bi, (w // spb) * (q * spb) + zv * spb + w % spb
                ),
            ))
        out_specs = []
        out_shapes = []
        for ri, _x in out_plan:
            s = strides[ri]
            spb = s * sc // lb
            mj = r // (q * s)
            out_specs.append(pl.BlockSpec(
                (sb, 1, q, lb),
                lambda bi, w, spb=spb: (bi, w // spb, 0, w % spb),
            ))
            out_shapes.append(
                jax.ShapeDtypeStruct((b, mj, q, s * sc), jnp.uint8)
            )
        outs = pl.pallas_call(
            kernel,
            grid=(b // sb, r * sc // (q * lb)),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=interpret,
        )(*operands)
        return [o.reshape(b, r * sc) for o in outs]

    return apply


def uncoupled_rows(
    q: int,
    strides: tuple[int, ...],
    kinds: tuple[tuple[str, ...], ...],
    pair_fwd,
    helpers: list,
    r: int,
    sc: int,
    interpret: bool = False,
):
    """helpers: one [B, r*sc] array per REAL member, (row, x) order.
    Returns one [B, r*sc] uncoupled-U array per non-aloof member in
    the same order (virtual members included — the inner MDS counts
    them as known rows; B2 classes hold the C placeholder)."""
    fn = _uncoupled_fn(
        q, tuple(strides),
        tuple(tuple(row) for row in kinds),
        (tuple(pair_fwd[0]), tuple(pair_fwd[1])),
        r, sc, _pick_sb(helpers[0].shape[0]), interpret,
    )
    return fn(*helpers)


# ---------------------------------------------- couple + scatter (c)
@functools.lru_cache(maxsize=64)
def _couple_scatter_fn(
    q: int,
    x_l: int,
    kinds: tuple[str, ...],
    pair_inv: tuple[tuple[int, int], tuple[int, int]],
    seq: int,
    r: int,
    sc: int,
    sb: int,
    interpret: bool,
):
    """Stage-c kernel builder: the lost row's q decoded U arrays plus
    its q-1 helper arrays in, the recovered chunk out.  Repair run j
    (``seq`` consecutive repair planes) produces output planes
    ``[j*q*seq, (j+1)*q*seq)`` — member x owns the x-th ``seq`` planes
    of the run — so the output view ``[B, num_seq, q, seq*sc]`` makes
    the whole scatter a rectangular block walk at any chunk size.

    ``kinds[x]`` is 'r'/'v' for the helper members (x_l's slot is
    ignored); ``pair_inv`` the (C_helper, U) coefficients recovering
    the lost coupled value, hi/lo."""
    helper_x = [
        x for x in range(q) if x != x_l and kinds[x] == "r"
    ]
    n_in = q + len(helper_x)
    lb = _pick_lb(sc, n_in + q, sb)
    spb = seq * sc // lb
    num_seq = r // seq
    hidx = {x: q + i for i, x in enumerate(helper_x)}

    def kernel(*refs):
        ins, out = refs[:n_in], refs[n_in]
        for x in range(q):
            u = bitcast_u8_to_i32(ins[x][:], interpret)
            if x == x_l:
                o = u
            else:
                c0, c1 = pair_inv[0] if x > x_l else pair_inv[1]
                h = (
                    bitcast_u8_to_i32(ins[hidx[x]][:], interpret)
                    if kinds[x] == "r" else None
                )
                o = _pair_i32(h, u, c0, c1)
            out[:, 0, x, :] = bitcast_i32_to_u8(o, interpret)

    @jax.jit
    def apply(*arrs):
        b = arrs[0].shape[0]
        return pl.pallas_call(
            kernel,
            grid=(b // sb, r * sc // lb),
            in_specs=[
                pl.BlockSpec((sb, lb), lambda bi, w: (bi, w))
                for _ in range(n_in)
            ],
            out_specs=pl.BlockSpec(
                (sb, 1, q, lb),
                lambda bi, w: (bi, w // spb, 0, w % spb),
            ),
            out_shape=jax.ShapeDtypeStruct(
                (b, num_seq, q, seq * sc), jnp.uint8
            ),
            interpret=interpret,
        )(*arrs).reshape(b, q * r * sc)

    return apply


def couple_scatter(
    q: int,
    x_l: int,
    kinds,
    pair_inv,
    udec: list,
    helpers: list,
    seq: int,
    r: int,
    sc: int,
    interpret: bool = False,
):
    """udec: q decoded lost-row U arrays [B, r*sc], ascending x;
    helpers: the REAL lost-row helper arrays [B, r*sc], ascending x
    with x_l and virtual members absent.  Returns the recovered chunk
    [B, sub_chunk_no*sc]."""
    fn = _couple_scatter_fn(
        q, x_l, tuple(kinds),
        (tuple(pair_inv[0]), tuple(pair_inv[1])),
        seq, r, sc, _pick_sb(udec[0].shape[0]), interpret,
    )
    return fn(*udec, *helpers)
