"""Pallas kernels for the CLAY aloof-free fast repair path.

The XLA formulation of the repair stages (stack rows -> plane-permute
gather -> fused pair transform; pair-combine -> stack -> inverse
permute) pays every intermediate against HBM: ~500 MB of traffic to
repair 45 MB of helper bytes. These kernels express the SAME algebra
as in-VMEM lane-slice networks — each plane is a contiguous ``sc``-lane
block of a shard row, so the pair transform and the final plane
scatter are static slice arithmetic inside one grid step, and HBM sees
each byte once in and once out.

Pair algebra (fixed by the construction's RS(2,2) coupling matrix,
codecs/clay.py): U = C ^ 2*(C_hi ^ C_lo) both ways, and its inverse
C_lost = C ^ inv2*(C ^ U). GF mul/div-by-2 run on int32 lanes holding
4 packed bytes (Mosaic cannot shift i8 vectors): shift, then mask the
cross-byte leak, then fold the reduction polynomial per byte. The
caller verifies the codec's coefficients match before routing here
(falls back to the XLA path otherwise).

Matches repair_one_lost_chunk (ErasureCodeClay.cc:454-699) restricted
to aloof == {}.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .pallas_encode import _emulate_i32_to_i8, _emulate_i8_to_i32

SB = 8   # minimum stripes per block (sublane granularity)
#: scatter-block lane budget: sb * sub_chunk_no * sc (the kernel's
#: VMEM footprint scales with the FULL chunk, not one plane packet).
#: Measured on v5e: 1 Mi lanes (SB=16, sub=64, sc=1024) compiles with
#: headroom; 2 Mi OOMs scoped VMEM.
MAX_SCATTER_LANES = 1 << 20


def _pick_sb(b: int, row_lanes: int, budget: int) -> int:
    """Largest block row count that divides the batch and keeps the
    block (sb * row_lanes output lanes) within the measured VMEM
    budget: 16 measured ~1 GB/s over 8 (fewer DMA grid steps)."""
    for sb in (16, 8):
        if b % sb == 0 and sb * row_lanes <= budget:
            return sb
    return SB


def _mul2_i32(xi):
    """Per-byte GF(2^8)/0x11D multiply-by-2 on packed int32 lanes."""
    return (
        ((xi & jnp.int32(0x7F7F7F7F)) << jnp.int32(1))
        ^ (((xi >> jnp.int32(7)) & jnp.int32(0x01010101))
           * jnp.int32(0x1D))
    )


def _div2_i32(xi):
    """Per-byte multiply by inv(2) = 142 on packed int32 lanes."""
    return (
        ((xi >> jnp.int32(1)) & jnp.int32(0x7F7F7F7F))
        ^ ((xi & jnp.int32(0x01010101)) * jnp.int32(0x8E))
    )


def _u8_to_i32(x, interpret):
    if interpret:
        return _emulate_i8_to_i32(x)
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.bitcast(x, jnp.int32)


def _i32_to_u8(p, interpret):
    if interpret:
        return _emulate_i32_to_i8(p).astype(jnp.uint8)
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.bitcast(p, jnp.int8).astype(jnp.uint8)


def supported(b: int, sc: int, sub_chunk_no: int) -> bool:
    """Batch must block on sublanes; plane packets must lane-align
    and the FULL-CHUNK scatter block must fit the VMEM budget (bigger
    sub-chunk counts or packets fall back to the XLA fast path)."""
    return (
        b % SB == 0
        and sc % 128 == 0
        and SB * sub_chunk_no * sc <= MAX_SCATTER_LANES
    )


@functools.lru_cache(maxsize=64)
def _uncoupled_fn(
    rows: tuple[int, ...],
    q: int,
    pvec_y: tuple[tuple[int, ...], ...],
    swap_p: tuple[tuple[tuple[int, ...], ...], ...],
    sc: int,
    sb: int,
    interpret: bool,
):
    """Stage-a kernel: (t-1)*q helper refs [B, P*sc] in, ONE stacked
    uncoupled tensor [B, (t-1)*q, P*sc] out (the exact input form the
    inner-MDS stacked matmul wants).

    ``pvec_y[ri][p]`` is plane p's digit for row rows[ri];
    ``swap_p[ri][x][p]`` the companion plane index for node x."""
    n_in = len(rows) * q
    P = len(pvec_y[0])

    # Greedy run merge: consecutive planes with the same digit class
    # and contiguous companions collapse into one wide slice op (the
    # minor free digit gives q-long runs — 4x fewer vector ops).
    plans: list[list[tuple[int, int, int, int]]] = []
    for ri in range(len(rows)):
        for x in range(q):
            runs = []
            p = 0
            while p < P:
                zv = pvec_y[ri][p]
                pp = swap_p[ri][x][p]
                end = p + 1
                while (
                    end < P
                    and pvec_y[ri][end] == zv
                    and swap_p[ri][x][end] == pp + (end - p)
                ):
                    end += 1
                runs.append((p, end, zv, pp))
                p = end
            plans.append(runs)

    def kernel(*refs):
        ins, out = refs[:n_in], refs[n_in]
        xi = [_u8_to_i32(r[:], interpret) for r in ins]
        for ri in range(len(rows)):
            for x in range(q):
                a32 = xi[ri * q + x]
                for p0, p1, zv, pp in plans[ri * q + x]:
                    a = a32[:, p0 * sc : p1 * sc]
                    if zv == x:
                        u = a
                    else:
                        b = xi[ri * q + zv][
                            :, pp * sc : (pp + p1 - p0) * sc
                        ]
                        u = a ^ _mul2_i32(a ^ b)
                    out[:, ri * q + x, p0 * sc : p1 * sc] = (
                        _i32_to_u8(u, interpret)
                    )

    @jax.jit
    def apply(*helpers):
        b = helpers[0].shape[0]
        return pl.pallas_call(
            kernel,
            grid=(b // sb,),
            in_specs=[
                pl.BlockSpec((sb, P * sc), lambda i: (i, 0))
                for _ in range(n_in)
            ],
            out_specs=pl.BlockSpec(
                (sb, n_in, P * sc), lambda i: (i, 0, 0)
            ),
            out_shape=jax.ShapeDtypeStruct(
                (b, n_in, P * sc), jnp.uint8
            ),
            interpret=interpret,
        )(*helpers)

    return apply


@functools.lru_cache(maxsize=64)
def _couple_scatter_fn(
    q: int,
    x_l: int,
    dst_p: tuple[tuple[int, ...], ...],
    P: int,
    sc: int,
    sub_chunk_no: int,
    sb: int,
    interpret: bool,
):
    """Stage-c kernel: q-1 lost-row helper refs [B, P*sc] plus the
    decoded lost-row U [B, q, P*sc] in, the recovered full chunk
    [B, sub_chunk_no*sc] out. ``dst_p[x][p]`` is the absolute plane
    each (row member x, repair plane p) pair produces."""

    # Merge contiguous destination planes (get_repair_subchunks hands
    # back runs, so the scatter is long contiguous lane stores).
    runs_x: list[list[tuple[int, int, int]]] = []
    for x in range(q):
        runs = []
        p = 0
        while p < P:
            z = dst_p[x][p]
            end = p + 1
            while end < P and dst_p[x][end] == z + (end - p):
                end += 1
            runs.append((p, end, z))
            p = end
        runs_x.append(runs)

    def kernel(*refs):
        helpers, udec, out = refs[: q - 1], refs[q - 1], refs[q]
        hi = 0
        for x in range(q):
            u32 = _u8_to_i32(udec[:, x, :], interpret)
            if x == x_l:
                for p0, p1, z in runs_x[x]:
                    out[:, z * sc : (z + p1 - p0) * sc] = _i32_to_u8(
                        u32[:, p0 * sc : p1 * sc], interpret
                    )
                continue
            h32 = _u8_to_i32(helpers[hi][:], interpret)
            hi += 1
            for p0, p1, z in runs_x[x]:
                a = h32[:, p0 * sc : p1 * sc]
                b = u32[:, p0 * sc : p1 * sc]
                out[:, z * sc : (z + p1 - p0) * sc] = _i32_to_u8(
                    a ^ _div2_i32(a ^ b), interpret
                )

    @jax.jit
    def apply(udec, *helpers):
        b = udec.shape[0]
        return pl.pallas_call(
            kernel,
            grid=(b // sb,),
            in_specs=[
                pl.BlockSpec((sb, P * sc), lambda i: (i, 0))
                for _ in range(q - 1)
            ]
            + [pl.BlockSpec((sb, q, P * sc), lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec(
                (sb, sub_chunk_no * sc), lambda i: (i, 0)
            ),
            out_shape=jax.ShapeDtypeStruct(
                (b, sub_chunk_no * sc), jnp.uint8
            ),
            interpret=interpret,
        )(*helpers, udec)

    return apply


def uncoupled_rows(
    rows: list[int],
    q: int,
    pvec_y: list[list[int]],
    swap_p,
    helpers: list,
    sc: int,
    interpret: bool = False,
):
    """helpers: (t-1)*q arrays [B, P*sc] (row-major, x within row).
    Returns the stacked uncoupled tensor [B, (t-1)*q, P*sc]."""
    fn = _uncoupled_fn(
        tuple(rows), q,
        tuple(tuple(v) for v in pvec_y),
        tuple(tuple(tuple(xs) for xs in r) for r in swap_p),
        sc,
        _pick_sb(
            helpers[0].shape[0],
            len(helpers) * len(pvec_y[0]) * sc,
            2 * MAX_SCATTER_LANES,
        ),
        interpret,
    )
    return fn(*helpers)


def couple_scatter(
    q: int,
    x_l: int,
    dst_p,
    udec,
    helpers: list,
    sc: int,
    sub_chunk_no: int,
    interpret: bool = False,
):
    """udec: [B, q, P*sc] decoded lost-row U; helpers: q-1 lost-row
    helper arrays [B, P*sc] (ascending x, lost member absent).
    Returns the recovered chunk [B, sub_chunk_no*sc]."""
    P = len(dst_p[0])
    fn = _couple_scatter_fn(
        q, x_l,
        tuple(tuple(v) for v in dst_p),
        P, sc, sub_chunk_no,
        _pick_sb(
            udec.shape[0], sub_chunk_no * sc, MAX_SCATTER_LANES
        ),
        interpret,
    )
    return fn(udec, *helpers)
