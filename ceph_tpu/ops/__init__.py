"""Device compute kernels: bit-plane GF engine, checksums, Pallas paths."""

from .bitplane import (  # noqa: F401
    unpack_bits,
    pack_bits,
    unpack_bits_lanes,
    pack_bits_lanes,
    mod2_matmul,
    gf_encode_bitplane,
    gf_mul_const_bytes,
    packet_mod2_apply,
    xor_bytes,
)
