"""Offline OSD store surgery — the ceph-objectstore-tool analog
(src/tools/ceph_objectstore_tool.cc).

Operates directly on one OSD's store directory while the daemon is
DOWN (the tool's defining property: it bypasses the cluster entirely):

    python -m ceph_tpu.objectstore_tool --data-path /c/osd.0 --op list
    python -m ceph_tpu.objectstore_tool --data-path /c/osd.0 \
        --op info 1:obj#s2
    python -m ceph_tpu.objectstore_tool --data-path /c/osd.0 \
        --op export --file dump.bin [objects...]
    python -m ceph_tpu.objectstore_tool --data-path /c/osd.1 \
        --op import --file dump.bin
    python -m ceph_tpu.objectstore_tool --data-path /c/osd.0 \
        --op remove 1:obj#s2
    python -m ceph_tpu.objectstore_tool --data-path /c/osd.0 --op fsck

ops mirrored from the reference: ``list`` (JSON lines, one per
object), ``info`` (size + parsed OI eversion + attrs + hinfo CRCs),
``export``/``import`` (portable crc-framed object archive — the
export/import used to salvage PG shards between OSDs), ``remove``,
and ``fsck`` (read every byte back; BlockStore csum verification makes
this the BlueStore-fsck deep mode).

Export format: one crc-framed record (store/framed_log) per object,
payload = JSON {oid, size, attrs{hex}} + b"\\0" + raw data. The
per-record crc32c gives the archive the same torn/corrupt detection
the stores' own WALs have.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

from ceph_tpu.store import framed_log


def open_store(data_path: str):
    from ceph_tpu.store import open_store as _open

    return _open(data_path)


def _obj_row(store, oid: str) -> dict:
    row: dict = {"oid": oid, "bytes": store.stat(oid)}
    try:
        from ceph_tpu.pipeline.rmw import OI_KEY, parse_oi

        size, ev = parse_oi(store.getattr(oid, OI_KEY))
        row["ro_size"] = size
        row["eversion"] = list(ev)
    except (FileNotFoundError, KeyError, ValueError):
        pass
    return row


def op_list(store, args) -> int:
    for oid in store.list_objects():
        print(json.dumps(_obj_row(store, oid)))
    return 0


def op_info(store, args) -> int:
    if not args.objects:
        print("info needs an object name", file=sys.stderr)
        return 2
    rc = 0
    for oid in args.objects:
        if not store.exists(oid):
            print(f"{oid}: not found", file=sys.stderr)
            rc = 1
            continue
        row = _obj_row(store, oid)
        attrs = store.getattrs(oid)
        row["attrs"] = {k: v.hex() for k, v in sorted(attrs.items())}
        try:
            from ceph_tpu.pipeline.hashinfo import HashInfo
            from ceph_tpu.pipeline.rmw import HINFO_KEY

            hinfo = HashInfo.from_bytes(attrs[HINFO_KEY])
            row["hinfo"] = {
                "total_chunk_size": hinfo.total_chunk_size,
                "cumulative_shard_crcs": [
                    hex(h) for h in hinfo.cumulative_shard_hashes
                ],
            }
        except (KeyError, ValueError):
            pass
        print(json.dumps(row))
    return rc


def op_export(store, args) -> int:
    if not args.file:
        print("export needs --file", file=sys.stderr)
        return 2
    oids = args.objects or store.list_objects()
    # build in a temp file so a failed export never leaves a torn
    # archive under the target name
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(args.file) or ".")
    os.close(fd)
    try:
        n = 0
        for oid in oids:
            if not store.exists(oid):
                print(f"{oid}: not found", file=sys.stderr)
                return 1
            data = store.read(oid)
            attrs = store.getattrs(oid)
            hdr = json.dumps(
                {
                    "oid": oid,
                    "size": len(data),
                    "attrs": {k: v.hex() for k, v in attrs.items()},
                }
            ).encode()
            framed_log.append(tmp, hdr + b"\0" + data, sync=False)
            n += 1
        os.replace(tmp, args.file)
    finally:
        if os.path.exists(tmp):  # any non-success path
            os.unlink(tmp)
    print(f"exported {n} objects to {args.file}")
    return 0


def op_import(store, args) -> int:
    from ceph_tpu.store import Transaction

    if not args.file or not os.path.exists(args.file):
        print("import needs an existing --file", file=sys.stderr)
        return 2
    with open(args.file, "rb") as f:
        raw = f.read()
    records, valid_end = framed_log.scan(raw)
    corrupt = valid_end != len(raw)
    if corrupt:
        print(
            f"archive corrupt past byte {valid_end}; importing the "
            "valid prefix only", file=sys.stderr,
        )
    # Pre-pass conflict check so the import is all-or-nothing: a
    # mid-archive abort after earlier records applied would leave the
    # store half-restored while reporting failure.
    if not args.force:
        clashes = []
        for payload in records:
            hdr_raw, _, _data = payload.partition(b"\0")
            oid = json.loads(hdr_raw.decode())["oid"]
            if store.exists(oid):
                clashes.append(oid)
        if clashes:
            for oid in clashes:
                print(
                    f"{oid}: exists (--force overwrites)", file=sys.stderr
                )
            return 1
    n = 0
    for payload in records:
        hdr_raw, _, data = payload.partition(b"\0")
        hdr = json.loads(hdr_raw.decode())
        oid = hdr["oid"]
        txn = Transaction().touch(oid)
        if store.exists(oid):
            txn.remove(oid).touch(oid)
        if data:
            txn.write(oid, 0, data)
        txn.truncate(oid, hdr["size"])
        for name, hexval in hdr["attrs"].items():
            txn.setattr(oid, name, bytes.fromhex(hexval))
        store.queue_transactions(txn)
        n += 1
    print(f"imported {n} objects")
    # a corrupt archive is a failed restore even though the valid
    # prefix was applied: scripts gating on the exit code must notice
    return 1 if corrupt else 0


def op_remove(store, args) -> int:
    from ceph_tpu.store import Transaction

    if not args.objects:
        print("remove needs object names", file=sys.stderr)
        return 2
    missing = [oid for oid in args.objects if not store.exists(oid)]
    if missing:  # all-or-nothing: fail before touching anything
        for oid in missing:
            print(f"{oid}: not found", file=sys.stderr)
        return 1
    for oid in args.objects:
        store.queue_transactions(Transaction().remove(oid))
        print(f"removed {oid}")
    return 0


def op_fsck(store, args) -> int:
    """Read every object fully (BlockStore verifies per-blob CRCs on
    read — the BlueStore fsck deep mode) and parse identity attrs."""
    bad = 0
    oids = store.list_objects()
    for oid in oids:
        try:
            store.read(oid)
        except Exception as e:
            print(f"{oid}: data error: {e}")
            bad += 1
            continue
        try:
            from ceph_tpu.pipeline.rmw import OI_KEY, parse_oi

            raw = store.getattrs(oid).get(OI_KEY)
            if raw is not None:
                parse_oi(raw)
        except ValueError as e:
            print(f"{oid}: corrupt OI attr: {e}")
            bad += 1
    print(f"fsck: {len(oids)} objects, {bad} errors")
    return 0 if bad == 0 else 1


OPS = {
    "list": op_list,
    "info": op_info,
    "export": op_export,
    "import": op_import,
    "remove": op_remove,
    "fsck": op_fsck,
}


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="ceph_tpu.objectstore_tool",
        description=__doc__.splitlines()[0],
    )
    p.add_argument("--data-path", required=True, help="OSD store dir")
    p.add_argument("--op", required=True, choices=sorted(OPS))
    p.add_argument("--file", help="archive path for export/import")
    p.add_argument(
        "--force", action="store_true",
        help="import: overwrite existing objects",
    )
    p.add_argument("objects", nargs="*", help="object names (store keys)")
    args = p.parse_args(argv)
    if not os.path.isdir(args.data_path):
        print(f"no store at {args.data_path}", file=sys.stderr)
        return 2
    store = open_store(args.data_path)
    try:
        return OPS[args.op](store, args)
    except BrokenPipeError:
        # output piped into head/less that exited: normal CLI usage
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0
    finally:
        if hasattr(store, "close"):
            store.close()


if __name__ == "__main__":
    sys.exit(main())
