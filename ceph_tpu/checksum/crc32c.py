"""CRC32C as a batched GF(2) matrix fold on the MXU.

The reference dispatches to per-arch carryless-multiply kernels
(src/common/crc32c.cc:19-32, src/arch/intel.c). TPUs have no clmul, so
we use linearity instead (SURVEY.md §7 "Hard parts"): with the
reflected Castagnoli polynomial, the CRC register after a message is

    crc(init, msg) = A_L @ init  ⊕  Σ_i  K_i @ bits(chunk_i)

over GF(2), where A_L is the 32x32 zero-message transition for L bytes
and K_i folds chunk i's bits directly to its final-position remainder
contribution. All K_i stack into one [S, 32, c*8] tensor, so a whole
batch of blocks is ONE int8 einsum with int32 accumulation (exact:
fan-in ≤ S*c*8 < 2^31) followed by ``& 1`` — the same mod-2 MXU
discipline as the EC engine (ceph_tpu.ops.bitplane).

Bit convention is LSB-first everywhere (bit b of byte j sits at index
j*8+b), matching the reflected-CRC register order so no bit reversal
is ever materialised.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .reference import CRC32C_POLY_REFLECTED, crc32c_ref

CHUNK_BYTES = 64  # fold granularity; 512-bit MXU contraction per chunk


def _bits32(v: int) -> np.ndarray:
    return np.array([(v >> i) & 1 for i in range(32)], dtype=np.uint8)


def _pack32(bits: np.ndarray) -> int:
    return int(sum(int(b) << i for i, b in enumerate(bits)))


@functools.lru_cache(maxsize=None)
def byte_step_matrix() -> bytes:
    """32x32 GF(2) matrix M: register transition for one ZERO byte.

    Column j = register after feeding one zero byte starting from the
    unit register e_j (the transition is linear, so unit responses
    define it).
    """
    m = np.zeros((32, 32), dtype=np.uint8)
    for j in range(32):
        m[:, j] = _bits32(crc32c_ref(1 << j, b"\x00"))
    return m.tobytes()


def _mat(b: bytes) -> np.ndarray:
    return np.frombuffer(b, dtype=np.uint8).reshape(32, 32)


#: public alias: consumers decoding zero_gap_matrix/byte_step_matrix
#: payloads must share ONE layout definition
mat32 = _mat


@functools.lru_cache(maxsize=None)
def zero_gap_matrix(nbytes: int) -> bytes:
    """A_n = M^n: transition across n zero bytes (square-and-multiply)."""
    result = np.eye(32, dtype=np.uint8)
    base = _mat(byte_step_matrix())
    n = nbytes
    while n:
        if n & 1:
            result = (result @ base) & 1
        base = (base @ base) & 1
        n >>= 1
    return result.astype(np.uint8).tobytes()


@functools.lru_cache(maxsize=None)
def chunk_fold_matrix(c: int = CHUNK_BYTES) -> bytes:
    """B_c [32, c*8]: remainder of a c-byte chunk from zero init.

    Column j*8+b = crc register after the chunk whose only set bit is
    bit b of byte j. Built from unit responses once per chunk size.
    """
    out = np.zeros((32, c * 8), dtype=np.uint8)
    for j in range(c):
        for b in range(8):
            msg = bytearray(c)
            msg[j] = 1 << b
            out[:, j * 8 + b] = _bits32(crc32c_ref(0, bytes(msg)))
    return out.tobytes()


@functools.lru_cache(maxsize=None)
def fold_tensor(block_bytes: int, c: int = CHUNK_BYTES) -> np.ndarray:
    """K [S, 32, c*8] with K_i = A_{(S-1-i)*c} @ B_c. One-time per
    (block size, chunk size); the TableCache discipline again."""
    assert block_bytes % c == 0, (block_bytes, c)
    s = block_bytes // c
    bc = np.frombuffer(chunk_fold_matrix(c), dtype=np.uint8).reshape(32, c * 8)
    k = np.empty((s, 32, c * 8), dtype=np.uint8)
    for i in range(s):
        a = _mat(zero_gap_matrix((s - 1 - i) * c))
        k[i] = (a @ bc) & 1
    return k


def _pick_chunk(block_bytes: int) -> int:
    c = CHUNK_BYTES
    while block_bytes % c:
        c >>= 1
    return c


@functools.lru_cache(maxsize=None)
def _host_fold(block_bytes: int, c: int):
    return (
        fold_tensor(block_bytes, c),
        _mat(zero_gap_matrix(block_bytes)),
    )


_device_cache: dict = {}


def _device_fold(block_bytes: int, c: int):
    """Device-resident (K, A_total) — uploaded once per block size, not
    per call (re-upload measured 10x+ slower through the device
    tunnel). Under an active trace (crc32c_device inside a jit or
    shard_map) the arrays become tracers, which must NOT be cached —
    they are embedded as compile-time constants instead."""
    kf, at = _host_fold(block_bytes, c)
    from ceph_tpu.utils.platform import trace_state_clean

    if not trace_state_clean():
        return jnp.asarray(kf, jnp.int8), jnp.asarray(at, jnp.int8)
    key = (block_bytes, c)
    if key not in _device_cache:
        _device_cache[key] = (
            jnp.asarray(kf, jnp.int8),
            jnp.asarray(at, jnp.int8),
        )
    return _device_cache[key]


def fold_blocks_bits(k_fold: jax.Array, data: jax.Array) -> jax.Array:
    """[B, L] uint8 x [S, 32, c*8] fold tensor -> [B, 32] int32
    remainder counts (mod 2 pending) — the shared einsum fold body."""
    c8 = k_fold.shape[-1]
    s = k_fold.shape[0]
    chunks = data.reshape(data.shape[0], s, c8 // 8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((chunks[..., None] >> shifts) & jnp.uint8(1)).reshape(
        data.shape[0], s, c8
    )
    return jnp.einsum(
        "src,bsc->br",
        k_fold,
        bits.astype(jnp.int8),
        preferred_element_type=jnp.int32,
    )


def init_bits32(init) -> jax.Array:
    return (
        (jnp.asarray(init, jnp.uint32) >> jnp.arange(32, dtype=jnp.uint32))
        & 1
    ).astype(jnp.int8)


def acc_to_crc32(acc: jax.Array) -> jax.Array:
    """[..., 32] int32 counts -> [...] uint32 (mod 2 + bit pack)."""
    crc_bits = (acc & 1).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(crc_bits * weights, axis=-1, dtype=jnp.uint32)


@functools.partial(jax.jit, static_argnames=("block_bytes",))
def _crc32c_kernel(
    data: jax.Array,  # [B, L] uint8
    init: jax.Array,  # scalar uint32
    k_fold: jax.Array,  # [S, 32, c*8] int8
    a_total: jax.Array,  # [32, 32] int8
    *,
    block_bytes: int,
) -> jax.Array:
    acc = fold_blocks_bits(k_fold, data)
    acc = acc + (
        a_total.astype(jnp.int32) @ init_bits32(init).astype(jnp.int32)
    )
    return acc_to_crc32(acc)


def crc32c_device(
    data: jax.Array, init: int | jax.Array = 0xFFFFFFFF
) -> jax.Array:
    """Per-block CRC32C of ``data`` [..., block_bytes] -> [...] uint32.

    Device analog of ``ceph_crc32c(init, block, len)`` vmapped over
    blocks; used by deep scrub and the ProtocolV2-analog segment
    checksums.
    """
    block_bytes = int(data.shape[-1])
    lead = data.shape[:-1]
    flat = data.reshape(-1, block_bytes)
    from ceph_tpu.utils import config

    from . import pallas_crc

    from . import backends

    if config.get("ec_use_pallas"):
        from ceph_tpu.ops.pallas_encode import on_tpu

        if on_tpu():
            if pallas_crc.supported(int(flat.shape[0]), block_bytes):
                backends.record("pallas", int(flat.size))
                return pallas_crc.crc32c_fold_pallas(flat, init).reshape(
                    lead
                )
            # the round-6 silent fallback, now visible: Pallas was
            # enabled on TPU but the shape could not tile
            backends.record("pallas_fallback")
            backends.warn_once(
                f"crc-untileable-{flat.shape[0]}x{block_bytes}",
                f"crc32c [{flat.shape[0]}, {block_bytes}] untileable "
                "for the Pallas fold; serving via einsum",
            )
    backends.record("einsum", int(flat.size))
    c = _pick_chunk(block_bytes)
    k_fold, a_total = _device_fold(block_bytes, c)
    out = _crc32c_kernel(
        flat,
        jnp.asarray(init, dtype=jnp.uint32),
        k_fold,
        a_total,
        block_bytes=block_bytes,
    )
    return out.reshape(lead)


def crc32c(init: int, data: bytes) -> int:
    """Host scalar API mirroring ``ceph_crc32c`` exactly — including the
    crc-of-zeros fast path the reference gets from crc32c_null
    (common/crc32c.h): runs the matrix transition, no byte loop."""
    if not data:
        return init & 0xFFFFFFFF
    if not any(data):
        a = _mat(zero_gap_matrix(len(data)))
        return _pack32((a @ _bits32(init)) & 1)
    return crc32c_ref(init, data)


def crc32c_concat(crc_a: int, crc_b_zero_init: int, len_b: int) -> int:
    """crc(A||B) from crc(A) and crc(B with zero init) — the bufferlist
    cached-crc "range concatenation" trick (common/crc32c.h,
    buffer.cc): crc(A||B) = A_{len_b} @ crc(A) ⊕ crc_0(B)."""
    a = _mat(zero_gap_matrix(len_b))
    return _pack32((a @ _bits32(crc_a)) & 1) ^ crc_b_zero_init


# -- fused-kernel csum plumbing ----------------------------------------
def crc32c_seed_shift(block_bytes: int, init: int) -> int:
    """The constant with crc(init, B) = crc(0, B) ^ shift for EVERY
    block of ``block_bytes`` (linearity: the init register's journey
    through the message is independent of the message bits). The
    fused encode+csum kernel emits ZERO-INIT per-block csums so one
    device pass serves every consumer seed — BlueStore blob csums
    (seed -1), HashInfo chains, wire csums — via this one XOR."""
    return _pack32(
        (_mat(zero_gap_matrix(block_bytes)) @ _bits32(init)) & 1
    )


def crc32c_chain(init: int, block_csums, block_bytes: int) -> int:
    """Fold ZERO-INIT per-block crc32c values into a running register:
    repeated range concatenation, cum' = A_block @ cum ⊕ crc_0(B_i).
    How HashInfo seeds cumulative shard hashes from fused-kernel csums
    without ever touching the bytes again."""
    a = _mat(zero_gap_matrix(block_bytes))
    reg = _bits32(init)
    for c0 in np.asarray(block_csums).reshape(-1):
        reg = ((a @ reg) & 1) ^ _bits32(int(c0))
    return _pack32(reg)


def crc32c_stream(data, init: int = 0xFFFFFFFF) -> int:
    """Cumulative crc32c of one byte stream, backend-routed: host
    scalar (native C when loaded) below ``csum_device_min_bytes``,
    device-batched fold above — whole blocks ride ``crc32c_device``
    zero-init and chain via ``crc32c_chain``; a ragged tail finishes
    on the host. Callers chain across pieces by passing the previous
    return as ``init`` (the deep-scrub stride loop)."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        buf = np.frombuffer(data, dtype=np.uint8)
    else:
        buf = np.ascontiguousarray(data, dtype=np.uint8).reshape(-1)
    from ceph_tpu.utils import config

    from . import backends
    from .host import crc32c as _host_crc

    n = int(buf.size)
    limit = int(config.get("csum_device_min_bytes"))
    if limit <= 0 or n < limit:
        backends.record("host", n)
        return _host_crc(init, buf.tobytes())
    cb = 65536 if n >= 4 * 65536 else 4096
    nb = n // cb
    blocks = buf[: nb * cb].reshape(nb, cb)
    c0 = np.asarray(crc32c_device(blocks, 0))
    reg = crc32c_chain(init, c0, cb)
    tail = buf[nb * cb :]
    if tail.size:
        reg = _host_crc(reg, tail.tobytes())
    return reg
