"""Pallas TPU kernel: batched CRC32C as an in-VMEM GF(2) fold.

The einsum formulation (checksum/crc32c.py) is algebraically right but
lets XLA materialize the unpacked bit tensor — an 8x expansion of the
input round-tripping HBM (measured ~33 GB/s hashed on v5e). This
kernel applies the EC encode kernel's discipline (ops/pallas_encode):
unpack bits in registers, one int8 MXU matmul per tile, never write
bits to memory — HBM traffic is the data itself plus a [B, 32] int32
accumulator.

Shape: blocks ride the sublane axis; the shared packed-int32 unpack
(ops/pallas_encode.unpack_bitplanes) produces planes as ROWS
(plane b, block), so the fold is 8 per-plane dots:

    acc[bt, :] = Σ_sub Σ_b  bits_b[bt, SUB] @ K_T[sub][b*SUB:(b+1)*SUB, 32]

with the fold tensor K (checksum/crc32c.fold_tensor) transposed and
permuted host-side to plane-major row order (row b*SUB + j = bit b
of byte j within the sub-block). Contraction per dot is SUB, not
SUB*8 — a streamed MXU column carries 16 data bytes instead of 8.
Long blocks fold across a second grid axis that revisits the
accumulator (read-modify-write on out_ref); parity (&1), the
init-register contribution, and the 32-bit pack are a tiny [B, 32]
epilogue outside the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

#: bytes of one block folded per grid step (contraction tile); with
#: the 8-plane int32 unpack intermediates, SUB x BLOCK_TILE is the
#: VMEM budget knob. 2048 x 512 measured best overall on v5e
#: (203/171/221 GB/s hashed at 4/16/64 KiB blocks vs ~33 for the
#: einsum path); larger SUB re-fetches more fold tensor per data byte
#: on multi-sub blocks, larger BT blows the 16M scoped-vmem limit.
SUB_BYTES = 2048
#: blocks per kernel instance (sublane tile)
BLOCK_TILE = 512


def plane_fold_kb(block_bytes: int) -> np.ndarray:
    """[8, block_bytes, 32] int8 per-plane fold matrices for ONE
    zero-init csum block: kb[b][p, :] = the 32 crc-register bits
    contributed by bit b of byte p of the block.

    This is the fold machinery the fused encode+checksum epilogue
    (ops/pallas_encode.gf_encode_csum_bitplane_pallas) keeps stationary
    in VMEM: the encode kernel already holds each tile's bit planes in
    registers, so per-block CRCs are 8 extra [rows, block] @ kb[b]
    dots — no second unpack, no second HBM pass."""
    from .crc32c import _pick_chunk, fold_tensor

    c = _pick_chunk(block_bytes)
    kf = fold_tensor(block_bytes, c)  # [S, 32, c*8]
    flat = np.transpose(kf, (1, 0, 2)).reshape(32, block_bytes * 8)
    out = np.empty((8, block_bytes, 32), dtype=np.int8)
    for b in range(8):
        out[b] = flat[:, b::8].T
    return out


def _plane_major_kt(k_fold: np.ndarray, c: int) -> np.ndarray:
    """[S, 32, c*8] fold tensor -> [nsub, SUB*8, 32] transposed K with
    rows in plane-major order (row b*SUB + j = bit b of byte j within
    the sub-block)."""
    s, _, c8 = k_fold.shape
    assert c8 == c * 8
    block_bytes = s * c
    sub = min(SUB_BYTES, block_bytes)
    assert block_bytes % sub == 0
    nsub = block_bytes // sub
    # K columns are (byte j within chunk, bit b) at index j*8+b; build
    # a flat [32, block_bytes*8] byte-major matrix first.
    flat = np.transpose(k_fold, (1, 0, 2)).reshape(32, block_bytes * 8)
    out = np.empty((nsub, sub * 8, 32), dtype=np.int8)
    for n in range(nsub):
        seg = flat[:, n * sub * 8 : (n + 1) * sub * 8]  # [32, sub*8]
        rows = np.empty((sub * 8, 32), dtype=np.int8)
        for b in range(8):
            # plane b: rows b*sub + j  <-  seg column j*8+b
            rows[b * sub : (b + 1) * sub, :] = seg[:, b::8].T
        out[n] = rows
    return out


def _make_kernel(bt: int, sub: int, interpret: bool):
    """Round-3 kernel, sharing the encode kernel's unpack
    (ops/pallas_encode.unpack_bitplanes): blocks ride sublanes, so
    the sublane bitcast packs 4 BLOCKS per int32 lane — each block's
    bits stay inside its own byte lane. Planes land as rows
    (b, block), so the fold becomes 8 per-plane dots against aligned
    [SUB, 32] slices of the fold tensor — contraction SUB instead of
    SUB*8, which doubles the useful bytes per streamed MXU column
    (16 vs 8)."""

    def kernel(kt_ref, data_ref, out_ref):
        from ceph_tpu.ops.pallas_encode import unpack_bitplanes

        d = data_ref[...]  # [BT, SUB] uint8
        bits = unpack_bitplanes(d, interpret)  # [8BT, SUB] (b, block)
        kt = kt_ref[0]  # [SUB*8, 32] rows b*SUB + j
        partial = jax.lax.dot_general(
            bits[0:bt], kt[0:sub],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        for b in range(1, 8):
            partial += jax.lax.dot_general(
                bits[b * bt : (b + 1) * bt],
                kt[b * sub : (b + 1) * sub],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [BT, 32]
        s = pl.program_id(1)

        @pl.when(s == 0)
        def _init():
            out_ref[...] = partial

        @pl.when(s != 0)
        def _acc():
            out_ref[...] += partial

    return kernel


@functools.partial(
    jax.jit, static_argnames=("block_bytes", "interpret")
)
def _fold_tiled(kt, data, block_bytes, interpret=False):
    nblocks = data.shape[0]
    nsub = kt.shape[0]
    sub = block_bytes // nsub
    bt = min(BLOCK_TILE, nblocks)
    acc = pl.pallas_call(
        _make_kernel(bt, sub, interpret),
        grid=(nblocks // bt, nsub),
        in_specs=[
            pl.BlockSpec((1,) + kt.shape[1:], lambda i, s: (s, 0, 0)),
            pl.BlockSpec((bt, sub), lambda i, s: (i, s)),
        ],
        out_specs=pl.BlockSpec((bt, 32), lambda i, s: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nblocks, 32), jnp.int32),
        interpret=interpret,
    )(kt, data)
    return acc


@functools.lru_cache(maxsize=16)
def _kt_cached(block_bytes: int, c: int):
    from .crc32c import fold_tensor

    return jnp.asarray(_plane_major_kt(fold_tensor(block_bytes, c), c))


def supported(nblocks: int, block_bytes: int) -> bool:
    """Tileable: enough blocks to fill a sublane tile evenly, a
    lane-aligned sub-fold, and a block count the sublane bitcast can
    pack (4 blocks per int32 lane)."""
    sub = min(SUB_BYTES, block_bytes)
    return (
        block_bytes % sub == 0
        and sub % 256 == 0
        and nblocks % min(BLOCK_TILE, nblocks) == 0
        and nblocks % 4 == 0
        and nblocks >= 8
    )


def crc32c_fold_pallas(
    data: jax.Array,  # [B, block_bytes] uint8
    init,
    interpret: bool | None = None,
) -> jax.Array:
    """Per-block CRC32C accumulator path on the MXU; same contract as
    the einsum kernel in checksum/crc32c."""
    from .crc32c import _pick_chunk, zero_gap_matrix

    if interpret is None:
        from ceph_tpu.ops.pallas_encode import on_tpu

        interpret = not on_tpu()
    nblocks, block_bytes = data.shape
    c = _pick_chunk(block_bytes)
    kt = _kt_cached(block_bytes, c)
    acc = _fold_tiled(kt, data, block_bytes, interpret=interpret)
    a_total = jnp.asarray(
        np.frombuffer(
            zero_gap_matrix(block_bytes), dtype=np.uint8
        ).reshape(32, 32),
        jnp.int32,
    )
    init_bits = (
        (jnp.asarray(init, jnp.uint32) >> jnp.arange(32, dtype=jnp.uint32))
        & 1
    ).astype(jnp.int32)
    acc = acc + (a_total @ init_bits)
    crc_bits = (acc & 1).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(crc_bits * weights, axis=-1, dtype=jnp.uint32)
