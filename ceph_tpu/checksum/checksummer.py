"""The Checksummer calculate/verify contract, batched on device.

Mirrors src/common/Checksummer.h:196-271: ``calculate`` fills a
per-block value array for a [offset, offset+length) range of a buffer;
``verify`` recomputes and returns the first bad byte offset (or -1)
plus the bad computed checksum. Five algorithms with the reference's
exact value widths (Checksummer.h:63-73): crc32c (u32), crc32c_16
(u16), crc32c_8 (u8), xxhash32 (u32), xxhash64 (u64).

Defaults match the reference: init_value -1 → all-ones register for
CRC (the BlueStore convention) and all-ones seed for xxhash.

Backend policy (the write-path fusion work, round 7): the crc32c
family routes host-staged batches below ``csum_device_min_bytes``
through the host scalar path (native C when loaded) — per-dispatch
device latency dwarfs the math there — and everything larger through
the device fold (Pallas MXU kernel on TPU when the shape tiles, XLA
einsum otherwise). Device-resident inputs always stay on device.
Every call records which backend served it (``checksum.backends``);
``Checksummer.last_backend`` exposes the choice per instance. Note
the HOT write path does not pass through here at all when the fused
encode+csum kernel runs (ops/pallas_encode.py): blob and HashInfo
csums then arrive precomputed from the encode dispatch, and this
facade is the verify/fallback tier.
"""

from __future__ import annotations

import numpy as np

from . import backends
from .crc32c import crc32c_device
from .xxhash import xxh32_device, xxh64_device


def crc32c_scalar(init: int, data) -> int:
    """Host scalar crc32c behind the Checksummer facade — THE
    sanctioned host-fallback entry point (import hygiene forbids
    ``checksum.host`` outside checksum/ and tests/, so the host path
    cannot silently creep back into pipeline/store code). Records the
    ``host`` backend."""
    from .host import crc32c as _host_crc

    if isinstance(data, np.ndarray):
        data = data.tobytes()
    backends.record("host", len(data))
    return _host_crc(init, data)


class _Alg:
    name: str
    value_dtype: np.dtype

    def digest_blocks(self, blocks: np.ndarray, init_value: int) -> np.ndarray:
        raise NotImplementedError


class _Crc32c(_Alg):
    name = "crc32c"
    value_dtype = np.dtype("<u4")
    mask = 0xFFFFFFFF

    def digest_blocks(self, blocks, init_value):
        init = init_value & 0xFFFFFFFF
        if isinstance(blocks, np.ndarray):
            from ceph_tpu.utils import config

            limit = int(config.get("csum_device_min_bytes"))
            if limit > 0 and blocks.nbytes < limit:
                from .host import crc32c as _host_crc

                backends.record("host", blocks.nbytes)
                out = np.fromiter(
                    (
                        _host_crc(init, blocks[i].tobytes())
                        for i in range(blocks.shape[0])
                    ),
                    dtype=np.uint32,
                    count=blocks.shape[0],
                )
                return (out & self.mask).astype(self.value_dtype)
        out = np.asarray(crc32c_device(blocks, init))
        return (out & self.mask).astype(self.value_dtype)


class _Crc32c16(_Crc32c):
    name = "crc32c_16"
    value_dtype = np.dtype("<u2")
    mask = 0xFFFF


class _Crc32c8(_Crc32c):
    name = "crc32c_8"
    value_dtype = np.dtype("u1")
    mask = 0xFF


class _XxHash32(_Alg):
    name = "xxhash32"
    value_dtype = np.dtype("<u4")

    def digest_blocks(self, blocks, init_value):
        seed = init_value & 0xFFFFFFFF
        backends.record("device", getattr(blocks, "nbytes", 0))
        return np.asarray(xxh32_device(blocks, seed)).astype(self.value_dtype)


class _XxHash64(_Alg):
    name = "xxhash64"
    value_dtype = np.dtype("<u8")

    def digest_blocks(self, blocks, init_value):
        seed = init_value & 0xFFFFFFFFFFFFFFFF
        backends.record("device", getattr(blocks, "nbytes", 0))
        hi, lo = xxh64_device(blocks, seed)
        return (
            (np.asarray(hi).astype(np.uint64) << np.uint64(32))
            | np.asarray(lo).astype(np.uint64)
        ).astype(self.value_dtype)


CSUM_ALGORITHMS: dict[str, _Alg] = {
    a.name: a() for a in (_Crc32c, _Crc32c16, _Crc32c8, _XxHash32, _XxHash64)
}

# CSumType enum values (Checksummer.h:15-23) for wire/attr parity.
CSUM_TYPE_IDS = {
    "none": 1,
    "xxhash32": 2,
    "xxhash64": 3,
    "crc32c": 4,
    "crc32c_16": 5,
    "crc32c_8": 6,
}


def csum_value_size(alg: str) -> int:
    """Checksummer::get_csum_value_size (Checksummer.h:63-73)."""
    if alg == "none":
        return 0
    return CSUM_ALGORITHMS[alg].value_dtype.itemsize


def _as_blocks(
    data, csum_block_size: int
) -> np.ndarray:
    if isinstance(data, (bytes, bytearray, memoryview)):
        buf = np.frombuffer(data, dtype=np.uint8)
    elif isinstance(data, np.ndarray):
        # Reinterpret the underlying BYTES (never value-cast): a csum
        # covers the wire/disk representation, not truncated values.
        buf = np.ascontiguousarray(data).view(np.uint8).reshape(-1)
    else:
        # Device (jax) array: keep it resident — blocks feed the device
        # kernels without a host round trip (a BlueStore blob already
        # in HBM verifies in place; only the tiny csum array returns).
        # Same bytes-not-values rule as the host branch: bitcast wider
        # dtypes to their little-endian byte representation.
        if str(data.dtype) != "uint8":
            import jax

            data = jax.lax.bitcast_convert_type(
                data.reshape(-1), np.uint8
            )
        buf = data.reshape(-1)
    if buf.size % csum_block_size:
        raise ValueError(
            f"length {buf.size} not a multiple of block {csum_block_size}"
        )
    return buf.reshape(-1, csum_block_size)


class Checksummer:
    """Block-checksum facade; one instance per (algorithm, block size),
    like a BlueStore blob's csum settings (bluestore_types.h).

    ``calculate``/``verify`` batch blocks through the backend policy
    at the top of this module (host scalar below the device
    threshold, Pallas/einsum device fold above, device-resident
    inputs always on device); after each call ``last_backend`` names
    the backend that actually ran — the observability the round-6
    silent-fallback advice asked for."""

    def __init__(self, alg: str, csum_block_size: int = 4096) -> None:
        if alg not in CSUM_ALGORITHMS:
            raise ValueError(
                f"unknown csum alg {alg!r}; choose from "
                f"{sorted(CSUM_ALGORITHMS)}"
            )
        if csum_block_size & (csum_block_size - 1):
            raise ValueError("csum_block_size must be a power of two")
        self.alg = CSUM_ALGORITHMS[alg]
        self.block_size = csum_block_size
        #: backend that served the most recent calculate/verify call
        #: ("host" | "pallas" | "einsum" | "device" | None)
        self.last_backend: str | None = None

    def calculate(
        self,
        data: bytes | np.ndarray,
        init_value: int = -1,
    ) -> np.ndarray:
        """Per-block checksum array for ``data`` (length must be a
        block multiple — the reference asserts the same,
        Checksummer.h:215)."""
        blocks = _as_blocks(data, self.block_size)
        out = self.alg.digest_blocks(blocks, init_value)
        self.last_backend = backends.last_backend()
        return out

    def verify(
        self,
        data: bytes | np.ndarray,
        csum_data: np.ndarray,
        offset: int = 0,
        init_value: int = -1,
    ) -> tuple[int, int]:
        """Returns (-1, 0) if clean, else (first bad byte offset,
        computed bad csum) — the verify contract of Checksummer.h:236.
        ``offset`` indexes into csum_data in block units * block_size;
        ``init_value`` must match the one used at calculate time."""
        blocks = _as_blocks(data, self.block_size)
        got = self.alg.digest_blocks(blocks, init_value)
        self.last_backend = backends.last_backend()
        expect = np.asarray(csum_data, dtype=self.alg.value_dtype)[
            offset // self.block_size : offset // self.block_size
            + blocks.shape[0]
        ]
        bad = np.nonzero(got != expect)[0]
        if bad.size == 0:
            return -1, 0
        first = int(bad[0])
        return offset + first * self.block_size, int(got[first])
