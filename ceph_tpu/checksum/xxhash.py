"""xxhash32/64 device kernels: scan over stripes, vmap over blocks.

Unlike CRC, xxhash is non-linear (multiplicative avalanche), so each
block is a true sequential chain — the TPU win is batch parallelism:
deep scrub checksums thousands of blocks at once, so the kernel scans
stripes while blocks fill the vector lanes. Mirrors the exact
algorithm Checksummer wraps (src/common/Checksummer.h:137-193,
vendored src/xxHash).

v2 layout (round 9): ACCUMULATORS ARE FULL-LANE VECTORS.  The round-3
kernel carried a ``[B, 4]`` accumulator — 4 active lanes of a 128-lane
VPU row, 3% utilization on every rotate/multiply, which is why the
honest r5 numbers sat at ~62 GB/s while crc32c's fold ran ~178.  Now
the block words are bitcast to uint32 and transposed ONCE to word-
major ``[W, B]`` (a single relayout pass over the input), so each of
the four xxhash accumulator lanes is its own ``[B]`` vector with
blocks on the VPU lanes: every round op runs at full width, and the
uint32-pair u64 emulation (``u64.py``) does too.  Constant multiplies
ride ``u64.mul_const`` — the constant's 16-bit limbs are Python ints,
so each round saves the limb-split round-trips of a generic mul.

Block sizes are static (csum_block_size), so tail handling is resolved
at trace time; csum blocks are whole stripes in practice (4K+), but
arbitrary static sizes are handled for parity with the reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import u64

_P32 = (2654435761, 2246822519, 3266489917, 668265263, 374761393)
_P64 = (
    11400714785074694791,
    14029467366897019727,
    1609587929392839161,
    9650029242287828579,
    2870177450012600261,
)


def _rotl32(x: jax.Array, r: int) -> jax.Array:
    return (x << r) | (x >> (32 - r))


def _unroll_split(nsteps: int, cap: int = 16) -> tuple[int, int]:
    """(f, main): the scan runs ``main // f`` steps with ``f`` rounds
    unrolled per step (per-step scan overhead dominated unfused
    bodies); the ``nsteps - main`` remainder stripes run eagerly after
    the scan. No divisibility requirement — a prime stripe count must
    not fall back to the 1-per-step cliff."""
    f = min(cap, nsteps)
    return f, (nsteps // f) * f


def _words_t(data: jax.Array, nwords: int) -> jax.Array:
    """[B, L] uint8 -> word-major [nwords, B] uint32 (little-endian —
    a free bitcast; TPU and the CPU CI backend agree).  The ONE
    transpose that puts blocks on the VPU lanes for the whole chain."""
    bsz = data.shape[0]
    w = jax.lax.bitcast_convert_type(
        data[:, : nwords * 4].reshape(bsz, nwords, 4), jnp.uint32
    )  # [B, W]
    return w.T  # [W, B]


@functools.partial(jax.jit, static_argnames=("block_bytes",))
def xxh32_kernel(
    data: jax.Array, seed: jax.Array, *, block_bytes: int
) -> jax.Array:
    """[B, L] uint8, scalar uint32 seed -> [B] uint32."""
    p1, p2, p3, p4, p5 = (jnp.uint32(p) for p in _P32)
    n = block_bytes
    bsz = data.shape[0]
    seed = seed.astype(jnp.uint32)
    wt = _words_t(data, n // 4) if n >= 4 else None
    i = 0
    if n >= 16:
        nstripes = n // 16
        init = tuple(
            jnp.broadcast_to(s, (bsz,))
            for s in (seed + p1 + p2, seed + p2, seed, seed - p1)
        )
        f, main = _unroll_split(nstripes)
        grouped = wt[: main * 4].reshape(main // f, f * 4, bsz)

        def round_(acc, lanes):  # acc 4x[B], lanes 4x[B]
            return tuple(
                _rotl32(acc[l] + lanes[l] * p2, 13) * p1
                for l in range(4)
            )

        def body(acc, group):  # group [f*4, B]
            for j in range(f):
                acc = round_(acc, [group[j * 4 + l] for l in range(4)])
            return acc, None

        acc, _ = jax.lax.scan(body, init, grouped)
        for s in range(main, nstripes):  # remainder stripes, eager
            acc = round_(acc, [wt[s * 4 + l] for l in range(4)])
        h = (
            _rotl32(acc[0], 1)
            + _rotl32(acc[1], 7)
            + _rotl32(acc[2], 12)
            + _rotl32(acc[3], 18)
        )
        i = nstripes * 16
    else:
        h = jnp.broadcast_to(seed + p5, (bsz,))
    h = h + jnp.uint32(n)
    while i + 4 <= n:
        h = _rotl32(h + wt[i // 4] * p3, 17) * p4
        i += 4
    while i < n:
        h = _rotl32(h + data[:, i].astype(jnp.uint32) * p5, 11) * p1
        i += 1
    h = h ^ (h >> 15)
    h = h * p2
    h = h ^ (h >> 13)
    h = h * p3
    return h ^ (h >> 16)


def _xxh64_round(acc, lane):
    return u64.mul_const(
        u64.rotl(u64.add(acc, u64.mul_const(lane, _P64[1])), 31),
        _P64[0],
    )


@functools.partial(jax.jit, static_argnames=("block_bytes",))
def xxh64_kernel(
    data: jax.Array, seed_hi: jax.Array, seed_lo: jax.Array, *, block_bytes: int
) -> tuple[jax.Array, jax.Array]:
    """[B, L] uint8 + seed (hi, lo) -> ((hi, lo) [B] uint32 pair)."""
    p1, p2, p3, p4, p5 = (u64.from_const(p) for p in _P64)
    n = block_bytes
    bsz = data.shape[0]
    seed = (
        jnp.broadcast_to(seed_hi.astype(jnp.uint32), (bsz,)),
        jnp.broadcast_to(seed_lo.astype(jnp.uint32), (bsz,)),
    )
    zero = (jnp.zeros((bsz,), jnp.uint32), jnp.zeros((bsz,), jnp.uint32))
    wt = _words_t(data, n // 4) if n >= 4 else None

    def lane64(widx: int):  # (hi, lo) [B] pair at word index
        return (wt[widx + 1], wt[widx])

    i = 0
    if n >= 32:
        nstripes = n // 32
        init = tuple(
            u64.add(seed, c)
            for c in (
                u64.add(p1, p2), p2, u64.from_const(0),
                # seed - P1 == seed + (~P1 + 1), two's complement.
                u64.from_const((-_P64[0]) & ((1 << 64) - 1)),
            )
        )
        f, main = _unroll_split(nstripes)
        grouped = wt[: main * 8].reshape(main // f, f * 8, bsz)

        def body(acc, group):  # group [f*8, B]
            for j in range(f):
                acc = tuple(
                    _xxh64_round(
                        acc[l],
                        (group[j * 8 + 2 * l + 1], group[j * 8 + 2 * l]),
                    )
                    for l in range(4)
                )
            return acc, None

        acc, _ = jax.lax.scan(body, init, grouped)
        for s in range(main, nstripes):  # remainder stripes, eager
            acc = tuple(
                _xxh64_round(acc[l], lane64(s * 8 + 2 * l))
                for l in range(4)
            )
        h = u64.add(
            u64.add(u64.rotl(acc[0], 1), u64.rotl(acc[1], 7)),
            u64.add(u64.rotl(acc[2], 12), u64.rotl(acc[3], 18)),
        )
        for l in range(4):
            h = u64.xor(h, _xxh64_round(zero, acc[l]))
            h = u64.add(u64.mul_const(h, _P64[0]), p4)
        i = nstripes * 32
    else:
        h = u64.add(seed, p5)
    h = u64.add(h, u64.from_const(n))
    while i + 8 <= n:
        h = u64.xor(h, _xxh64_round(zero, lane64(i // 4)))
        h = u64.add(u64.mul_const(u64.rotl(h, 27), _P64[0]), p4)
        i += 8
    if i + 4 <= n:
        lane = (jnp.zeros((bsz,), jnp.uint32), wt[i // 4])
        h = u64.xor(h, u64.mul_const(lane, _P64[0]))
        h = u64.add(u64.mul_const(u64.rotl(h, 23), _P64[1]), p3)
        i += 4
    while i < n:
        byte = (
            jnp.zeros((bsz,), jnp.uint32),
            data[:, i].astype(jnp.uint32),
        )
        h = u64.xor(h, u64.mul_const(byte, _P64[4]))
        h = u64.mul_const(u64.rotl(h, 11), _P64[0])
        i += 1
    h = u64.xor(h, u64.shr(h, 33))
    h = u64.mul_const(h, _P64[1])
    h = u64.xor(h, u64.shr(h, 29))
    h = u64.mul_const(h, _P64[2])
    h = u64.xor(h, u64.shr(h, 32))
    return h


def xxh32_device(data: jax.Array, seed: int | jax.Array = 0) -> jax.Array:
    """Per-block xxhash32: [..., L] uint8 -> [...] uint32."""
    lead = data.shape[:-1]
    flat = data.reshape(-1, data.shape[-1])
    out = xxh32_kernel(
        flat, jnp.asarray(seed, jnp.uint32), block_bytes=int(data.shape[-1])
    )
    return out.reshape(lead)


def xxh64_device(
    data: jax.Array, seed: int = 0
) -> tuple[jax.Array, jax.Array]:
    """Per-block xxhash64: [..., L] uint8 -> (hi, lo) [...] uint32 pair."""
    lead = data.shape[:-1]
    flat = data.reshape(-1, data.shape[-1])
    hi, lo = xxh64_kernel(
        flat,
        jnp.asarray((seed >> 32) & 0xFFFFFFFF, jnp.uint32),
        jnp.asarray(seed & 0xFFFFFFFF, jnp.uint32),
        block_bytes=int(data.shape[-1]),
    )
    return hi.reshape(lead), lo.reshape(lead)
