"""xxhash32/64 device kernels: scan over stripes, vmap over blocks.

Unlike CRC, xxhash is non-linear (multiplicative avalanche), so each
block is a true sequential chain — the TPU win is batch parallelism:
deep scrub checksums thousands of blocks at once, so the kernel scans
stripes with a [B, 4]-lane accumulator on the VPU while blocks fill
the vector lanes. Mirrors the exact algorithm Checksummer wraps
(src/common/Checksummer.h:137-193, vendored src/xxHash).

Block sizes are static (csum_block_size), so tail handling is resolved
at trace time; csum blocks are whole stripes in practice (4K+), but
arbitrary static sizes are handled for parity with the reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import u64

_P32 = (2654435761, 2246822519, 3266489917, 668265263, 374761393)
_P64 = (
    11400714785074694791,
    14029467366897019727,
    1609587929392839161,
    9650029242287828579,
    2870177450012600261,
)


def _rotl32(x: jax.Array, r: int) -> jax.Array:
    return (x << r) | (x >> (32 - r))


def _unroll_split(nsteps: int, cap: int = 16) -> tuple[int, int]:
    """(f, main): the scan runs ``main // f`` steps with ``f`` rounds
    unrolled per step (per-step scan overhead on tiny [B, 4] bodies
    dominated the whole kernel); the ``nsteps - main`` remainder
    stripes run eagerly after the scan. No divisibility requirement —
    a prime stripe count must not fall back to the 1-per-step cliff."""
    f = min(cap, nsteps)
    return f, (nsteps // f) * f


def _le32(b: jax.Array) -> jax.Array:
    """[..., 4] uint8 -> [...] uint32 little-endian — a free bitcast
    (TPU and the CPU CI backend are both little-endian)."""
    return jax.lax.bitcast_convert_type(b, jnp.uint32)


@functools.partial(jax.jit, static_argnames=("block_bytes",))
def xxh32_kernel(
    data: jax.Array, seed: jax.Array, *, block_bytes: int
) -> jax.Array:
    """[B, L] uint8, scalar uint32 seed -> [B] uint32."""
    p1, p2, p3, p4, p5 = (jnp.uint32(p) for p in _P32)
    n = block_bytes
    bsz = data.shape[0]
    seed = seed.astype(jnp.uint32)
    i = 0
    if n >= 16:
        nstripes = n // 16
        init = jnp.broadcast_to(
            jnp.stack([seed + p1 + p2, seed + p2, seed, seed - p1]),
            (bsz, 4),
        )
        f, main = _unroll_split(nstripes)
        # Keep the scanned operand in BYTES ([G, B, f*16] uint8) and
        # build the uint32 lanes inside the body: pre-materializing
        # _le32 over the whole input wrote a 4x-expanded uint32
        # tensor (plus its transpose) through HBM — 5x the kernel's
        # true traffic and the actual bottleneck.
        grouped = (
            data[:, : main * 16]
            .reshape(bsz, main // f, f * 16)
            .swapaxes(0, 1)
        )

        def body(acc, group):  # group [B, f*16] uint8
            lanes = _le32(group.reshape(bsz, f, 4, 4))  # [B, f, 4]
            for j in range(f):
                acc = acc + lanes[:, j] * p2
                acc = _rotl32(acc, 13) * p1
            return acc, None

        acc, _ = jax.lax.scan(body, init, grouped)
        for s in range(main, nstripes):  # remainder stripes, eager
            lanes = _le32(
                data[:, s * 16 : (s + 1) * 16].reshape(bsz, 4, 4)
            )
            acc = acc + lanes * p2
            acc = _rotl32(acc, 13) * p1
        h = (
            _rotl32(acc[:, 0], 1)
            + _rotl32(acc[:, 1], 7)
            + _rotl32(acc[:, 2], 12)
            + _rotl32(acc[:, 3], 18)
        )
        i = nstripes * 16
    else:
        h = jnp.broadcast_to(seed + p5, (bsz,))
    h = h + jnp.uint32(n)
    while i + 4 <= n:
        lane = _le32(data[:, i : i + 4])
        h = _rotl32(h + lane * p3, 17) * p4
        i += 4
    while i < n:
        h = _rotl32(h + data[:, i].astype(jnp.uint32) * p5, 11) * p1
        i += 1
    h = h ^ (h >> 15)
    h = h * p2
    h = h ^ (h >> 13)
    h = h * p3
    return h ^ (h >> 16)


def _le64_pair(b: jax.Array):
    """[..., 8] uint8 -> (hi, lo) uint32 little-endian.

    A BITCAST, not byte shifts: the lanes are already little-endian
    contiguous bytes, so reinterpreting [..., 2, 4] uint8 as uint32
    is free — the shift-assembly this replaces cost ~10 VPU ops per
    lane and measured up to 38% of the whole xxh64 kernel (round 4)."""
    w = jax.lax.bitcast_convert_type(
        b.reshape(b.shape[:-1] + (2, 4)), jnp.uint32
    )
    return (w[..., 1], w[..., 0])


def _xxh64_round(acc, lane):
    p1 = u64.from_const(_P64[0])
    p2 = u64.from_const(_P64[1])
    return u64.mul(u64.rotl(u64.add(acc, u64.mul(lane, p2)), 31), p1)


@functools.partial(jax.jit, static_argnames=("block_bytes",))
def xxh64_kernel(
    data: jax.Array, seed_hi: jax.Array, seed_lo: jax.Array, *, block_bytes: int
) -> tuple[jax.Array, jax.Array]:
    """[B, L] uint8 + seed (hi, lo) -> ((hi, lo) [B] uint32 pair)."""
    p1, p2, p3, p4, p5 = (u64.from_const(p) for p in _P64)
    n = block_bytes
    bsz = data.shape[0]
    seed = (
        jnp.broadcast_to(seed_hi.astype(jnp.uint32), (bsz,)),
        jnp.broadcast_to(seed_lo.astype(jnp.uint32), (bsz,)),
    )
    zero = (jnp.zeros((bsz,), jnp.uint32), jnp.zeros((bsz,), jnp.uint32))
    i = 0
    if n >= 32:
        nstripes = n // 32
        init4 = [
            u64.add(seed, u64.add(p1, p2)),
            u64.add(seed, p2),
            seed,
            # seed - P1 == seed + (~P1 + 1) — two's complement negation.
            u64.add(seed, u64.from_const((-_P64[0]) & ((1 << 64) - 1))),
        ]
        init = (
            jnp.stack([a[0] for a in init4], axis=-1),  # hi [B, 4]
            jnp.stack([a[1] for a in init4], axis=-1),  # lo [B, 4]
        )

        f, main = _unroll_split(nstripes)
        # bytes stay bytes until inside the body (see xxh32_kernel)
        grouped = (
            data[:, : main * 32]
            .reshape(bsz, main // f, f * 32)
            .swapaxes(0, 1)
        )

        def body(acc, group):  # group [B, f*32] uint8
            hi, lo = _le64_pair(
                group.reshape(bsz, f, 4, 8)
            )  # each [B, f, 4]
            for j in range(f):
                acc = _xxh64_round(acc, (hi[:, j], lo[:, j]))
            return acc, None

        acc, _ = jax.lax.scan(body, init, grouped)
        for s in range(main, nstripes):  # remainder stripes, eager
            hi, lo = _le64_pair(
                data[:, s * 32 : (s + 1) * 32].reshape(bsz, 4, 8)
            )
            acc = _xxh64_round(acc, (hi, lo))
        accs = [(acc[0][:, j], acc[1][:, j]) for j in range(4)]
        h = u64.add(
            u64.add(u64.rotl(accs[0], 1), u64.rotl(accs[1], 7)),
            u64.add(u64.rotl(accs[2], 12), u64.rotl(accs[3], 18)),
        )
        for j in range(4):
            h = u64.xor(h, _xxh64_round(zero, accs[j]))
            h = u64.add(u64.mul(h, p1), p4)
        i = nstripes * 32
    else:
        h = u64.add(seed, p5)
    h = u64.add(h, u64.from_const(n))
    while i + 8 <= n:
        lane = _le64_pair(data[:, i : i + 8])
        h = u64.xor(h, _xxh64_round(zero, lane))
        h = u64.add(u64.mul(u64.rotl(h, 27), p1), p4)
        i += 8
    if i + 4 <= n:
        lane = (jnp.zeros((bsz,), jnp.uint32), _le32(data[:, i : i + 4]))
        h = u64.xor(h, u64.mul(lane, p1))
        h = u64.add(u64.mul(u64.rotl(h, 23), p2), p3)
        i += 4
    while i < n:
        byte = (
            jnp.zeros((bsz,), jnp.uint32),
            data[:, i].astype(jnp.uint32),
        )
        h = u64.xor(h, u64.mul(byte, p5))
        h = u64.mul(u64.rotl(h, 11), p1)
        i += 1
    h = u64.xor(h, u64.shr(h, 33))
    h = u64.mul(h, p2)
    h = u64.xor(h, u64.shr(h, 29))
    h = u64.mul(h, p3)
    h = u64.xor(h, u64.shr(h, 32))
    return h


def xxh32_device(data: jax.Array, seed: int | jax.Array = 0) -> jax.Array:
    """Per-block xxhash32: [..., L] uint8 -> [...] uint32."""
    lead = data.shape[:-1]
    flat = data.reshape(-1, data.shape[-1])
    out = xxh32_kernel(
        flat, jnp.asarray(seed, jnp.uint32), block_bytes=int(data.shape[-1])
    )
    return out.reshape(lead)


def xxh64_device(
    data: jax.Array, seed: int = 0
) -> tuple[jax.Array, jax.Array]:
    """Per-block xxhash64: [..., L] uint8 -> (hi, lo) [...] uint32 pair."""
    lead = data.shape[:-1]
    flat = data.reshape(-1, data.shape[-1])
    hi, lo = xxh64_kernel(
        flat,
        jnp.asarray((seed >> 32) & 0xFFFFFFFF, jnp.uint32),
        jnp.asarray(seed & 0xFFFFFFFF, jnp.uint32),
        block_bytes=int(data.shape[-1]),
    )
    return hi.reshape(lead), lo.reshape(lead)
