"""Host checksum dispatch — the ``ceph_crc32c`` runtime-probe analog.

The reference probes CPU features once and routes every crc32c call to
the fastest implementation (src/common/crc32c.cc:19-32). Here: native
(SSE4.2 hardware or slicing-by-8, ceph_tpu.native) when the C++ tier
loads, the bitwise Python oracle otherwise. Both are bit-identical —
tests/test_native.py proves it on random vectors.

The device-batched Checksummer kernels (checksum/crc32c.py) remain the
bulk path; this is for host-side hot spots: wire frame CRCs, HashInfo
chaining, deep-scrub verification.
"""

from __future__ import annotations

from . import reference as _ref


def _select():
    try:
        from ceph_tpu import native

        if native.available():
            return native.crc32c
    except Exception:
        pass
    return _ref.crc32c_ref


def _select_wire():
    # The wire-frame hot path: zero-copy bytes entry (no numpy
    # round-trip per segment) when the native tier loads, the bitwise
    # oracle otherwise. Bit-identical across backends — pinned by the
    # cross-backend oracle in tests/test_wire_native.py.
    try:
        from ceph_tpu import native

        if native.available():
            return native.crc32c_bytes
    except Exception:
        pass
    return _ref.crc32c_ref


crc32c = _select()
crc32c_wire = _select_wire()
