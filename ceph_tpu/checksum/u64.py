"""uint64 arithmetic as uint32 pairs — xxhash64 lanes without x64 mode.

JAX runs with 32-bit ints here (x64 would globally change dtypes and
TPUs emulate 64-bit anyway), so xxh64's multiplies/rotates operate on
``(hi, lo)`` uint32 array pairs. Multiplication builds the low 64 bits
from 16-bit limb products (each partial < 2^32, so uint32 wrap-around
arithmetic with explicit carries is exact).
"""

from __future__ import annotations

import jax.numpy as jnp

U64 = tuple  # (hi, lo) uint32 arrays

# Plain python int: weak-typed under jnp ops, so no jax array (and hence
# no backend initialization) is created at import time — the driver's
# virtual-CPU-mesh dryrun depends on `import ceph_tpu` staying inert.
_MASK16 = 0xFFFF


def u64(hi, lo) -> U64:
    return (jnp.asarray(hi, jnp.uint32), jnp.asarray(lo, jnp.uint32))


def from_const(v: int) -> U64:
    return (
        jnp.uint32((v >> 32) & 0xFFFFFFFF),
        jnp.uint32(v & 0xFFFFFFFF),
    )


def add(a: U64, b: U64) -> U64:
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(jnp.uint32)
    return (a[0] + b[0] + carry, lo)


def xor(a: U64, b: U64) -> U64:
    return (a[0] ^ b[0], a[1] ^ b[1])


def _mul32_full(a, b):
    """Full 64-bit product of two uint32 arrays -> (hi, lo) uint32."""
    al, ah = a & _MASK16, a >> 16
    bl, bh = b & _MASK16, b >> 16
    ll = al * bl
    lh = al * bh
    hl = ah * bl
    hh = ah * bh
    # lo = ll + ((lh + hl) << 16), carries tracked limb-wise.
    mid = lh + (hl & _MASK16)  # fits: < 2^32 + 2^16... track carefully
    mid_carry = (mid < lh).astype(jnp.uint32)
    lo = ll + (mid << 16)
    lo_carry = (lo < ll).astype(jnp.uint32)
    hi = hh + (hl >> 16) + (mid >> 16) + (mid_carry << 16) + lo_carry
    return (hi, lo)


def mul(a: U64, b: U64) -> U64:
    """Low 64 bits of a*b (wrap-around, as uint64 multiply does)."""
    hi, lo = _mul32_full(a[1], b[1])
    hi = hi + a[1] * b[0] + a[0] * b[1]
    return (hi, lo)


def mul_const(a: U64, c: int) -> U64:
    """Low 64 bits of a * constant.  The constant's 16-bit limbs stay
    Python ints (weak-typed scalars), so the per-call limb splits of
    the generic ``mul`` — two mask/shift round-trips per operand —
    drop out; xxh64's per-stripe rounds are all constant multiplies."""
    cl, ch = c & 0xFFFFFFFF, (c >> 32) & 0xFFFFFFFF
    al, ah = a[1] & _MASK16, a[1] >> 16
    # 16-bit limbs stay weak-typed python ints; the full 32-bit words
    # must wrap in uint32 explicitly (>= 2^31 overflows weak int32)
    bl, bh = cl & _MASK16, cl >> 16
    cl, ch = jnp.uint32(cl), jnp.uint32(ch)
    ll = al * bl
    lh = al * bh
    hl = ah * bl
    hh = ah * bh
    mid = lh + (hl & _MASK16)
    mid_carry = (mid < lh).astype(jnp.uint32)
    lo = ll + (mid << 16)
    lo_carry = (lo < ll).astype(jnp.uint32)
    hi = hh + (hl >> 16) + (mid >> 16) + (mid_carry << 16) + lo_carry
    hi = hi + a[1] * ch + a[0] * cl
    return (hi, lo)


def rotl(a: U64, r: int) -> U64:
    r &= 63
    if r == 0:
        return a
    if r == 32:
        return (a[1], a[0])
    if r < 32:
        hi = (a[0] << r) | (a[1] >> (32 - r))
        lo = (a[1] << r) | (a[0] >> (32 - r))
        return (hi, lo)
    s = r - 32
    hi = (a[1] << s) | (a[0] >> (32 - s))
    lo = (a[0] << s) | (a[1] >> (32 - s))
    return (hi, lo)


def shr(a: U64, r: int) -> U64:
    r &= 63
    if r == 0:
        return a
    if r == 32:
        return (jnp.zeros_like(a[0]), a[0])
    if r < 32:
        lo = (a[1] >> r) | (a[0] << (32 - r))
        return (a[0] >> r, lo)
    return (jnp.zeros_like(a[0]), a[0] >> (r - 32))


def to_py(a: U64) -> int:
    """Scalar (hi, lo) -> python int (for tests/digest extraction)."""
    return (int(a[0]) << 32) | int(a[1])
