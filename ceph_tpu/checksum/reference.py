"""Scalar host reference implementations (test oracles).

``crc32c_ref`` matches ``ceph_crc32c(init, data, len)`` semantics —
raw register in/out, reflected Castagnoli polynomial, NO final XOR
(verified against src/test/common/test_crc32c.cc:21-43 vectors).
``xxh32_ref``/``xxh64_ref`` match the vendored xxHash used by
Checksummer (src/common/Checksummer.h:137-193), verified against the
canonical XXH32/XXH64 test vectors.
"""

from __future__ import annotations

CRC32C_POLY_REFLECTED = 0x82F63B78

_M32 = 0xFFFFFFFF
_M64 = 0xFFFFFFFFFFFFFFFF


def crc32c_ref(init: int, data: bytes) -> int:
    crc = init & _M32
    for byte in data:
        crc ^= byte
        for _ in range(8):
            crc = (crc >> 1) ^ (CRC32C_POLY_REFLECTED if crc & 1 else 0)
    return crc


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def _rotl64(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


_P32 = (2654435761, 2246822519, 3266489917, 668265263, 374761393)
_P64 = (
    11400714785074694791,
    14029467366897019727,
    1609587929392839161,
    9650029242287828579,
    2870177450012600261,
)


def xxh32_ref(data: bytes, seed: int = 0) -> int:
    p1, p2, p3, p4, p5 = _P32
    n = len(data)
    i = 0
    if n >= 16:
        acc = [
            (seed + p1 + p2) & _M32,
            (seed + p2) & _M32,
            seed & _M32,
            (seed - p1) & _M32,
        ]
        while i + 16 <= n:
            for j in range(4):
                lane = int.from_bytes(data[i + 4 * j : i + 4 * j + 4], "little")
                a = (acc[j] + lane * p2) & _M32
                acc[j] = (_rotl32(a, 13) * p1) & _M32
            i += 16
        h = (
            _rotl32(acc[0], 1)
            + _rotl32(acc[1], 7)
            + _rotl32(acc[2], 12)
            + _rotl32(acc[3], 18)
        ) & _M32
    else:
        h = (seed + p5) & _M32
    h = (h + n) & _M32
    while i + 4 <= n:
        lane = int.from_bytes(data[i : i + 4], "little")
        h = (h + lane * p3) & _M32
        h = (_rotl32(h, 17) * p4) & _M32
        i += 4
    while i < n:
        h = (h + data[i] * p5) & _M32
        h = (_rotl32(h, 11) * p1) & _M32
        i += 1
    h ^= h >> 15
    h = (h * p2) & _M32
    h ^= h >> 13
    h = (h * p3) & _M32
    h ^= h >> 16
    return h


def _xxh64_round(acc: int, lane: int) -> int:
    p1, p2 = _P64[0], _P64[1]
    acc = (acc + lane * p2) & _M64
    return (_rotl64(acc, 31) * p1) & _M64


def xxh64_ref(data: bytes, seed: int = 0) -> int:
    p1, p2, p3, p4, p5 = _P64
    n = len(data)
    i = 0
    if n >= 32:
        acc = [
            (seed + p1 + p2) & _M64,
            (seed + p2) & _M64,
            seed & _M64,
            (seed - p1) & _M64,
        ]
        while i + 32 <= n:
            for j in range(4):
                lane = int.from_bytes(data[i + 8 * j : i + 8 * j + 8], "little")
                acc[j] = _xxh64_round(acc[j], lane)
            i += 32
        h = (
            _rotl64(acc[0], 1)
            + _rotl64(acc[1], 7)
            + _rotl64(acc[2], 12)
            + _rotl64(acc[3], 18)
        ) & _M64
        for j in range(4):
            h ^= _xxh64_round(0, acc[j])
            h = (h * p1 + p4) & _M64
    else:
        h = (seed + p5) & _M64
    h = (h + n) & _M64
    while i + 8 <= n:
        lane = int.from_bytes(data[i : i + 8], "little")
        h ^= _xxh64_round(0, lane)
        h = (_rotl64(h, 27) * p1 + p4) & _M64
        i += 8
    if i + 4 <= n:
        lane = int.from_bytes(data[i : i + 4], "little")
        h ^= (lane * p1) & _M64
        h = (_rotl64(h, 23) * p2 + p3) & _M64
        i += 4
    while i < n:
        h ^= (data[i] * p5) & _M64
        h = (_rotl64(h, 11) * p1) & _M64
        i += 1
    h ^= h >> 33
    h = (h * p2) & _M64
    h ^= h >> 29
    h = (h * p3) & _M64
    h ^= h >> 32
    return h
