"""Block checksumming — the BlueStore/deep-scrub integrity family.

Implements the five ``Checksummer`` algorithms of the reference
(src/common/Checksummer.h:15-23: crc32c, crc32c_16, crc32c_8,
xxhash32, xxhash64) with the same block-granular calculate/verify
contract (Checksummer.h:196-271), plus the raw ``ceph_crc32c``-style
entry point (src/common/crc32c.h).

TPU lowering: CRC32C is GF(2)-linear in the message bits, so a whole
batch of blocks reduces to one int8 MXU matmul against precomputed
fold matrices (``crc32c.py``). xxhash is genuinely sequential per
block, so it runs as a ``lax.scan`` over stripes vmapped across blocks
(``xxhash.py``), with 64-bit lanes emulated as uint32 pairs
(``u64.py``) — JAX x64 stays off.
"""

from . import backends
from .checksummer import (
    CSUM_ALGORITHMS,
    Checksummer,
    crc32c_scalar,
    csum_value_size,
)
from .crc32c import crc32c as crc32c_host
from .host import crc32c_wire
from .crc32c import (
    crc32c_chain,
    crc32c_device,
    crc32c_seed_shift,
    crc32c_stream,
)
from .reference import crc32c_ref, xxh32_ref, xxh64_ref

__all__ = [
    "CSUM_ALGORITHMS",
    "Checksummer",
    "backends",
    "crc32c_chain",
    "crc32c_host",
    "crc32c_device",
    "crc32c_ref",
    "crc32c_scalar",
    "crc32c_seed_shift",
    "crc32c_stream",
    "crc32c_wire",
    "csum_value_size",
    "xxh32_ref",
    "xxh64_ref",
]
