"""Checksum backend observability.

``checksum/pallas_crc.supported()`` used to fall back silently: a
bench or test had no way to tell whether a crc actually rode the
Pallas MXU fold, the XLA einsum engine, the host native/bitwise
scalar path, or arrived precomputed from the fused encode+csum
kernel. Every routing decision now records here — plain module-level
counters (no locks: increments are GIL-atomic and these sit on
messenger/store hot paths), a last-backend marker the ``Checksummer``
facade surfaces per call, and a log-once for the silent-fallback case.

Backends:
- ``pallas``  — the MXU fold kernel (checksum/pallas_crc.py)
- ``einsum``  — the XLA einsum fold (checksum/crc32c.py)
- ``host``    — host scalar path (native C or the bitwise oracle)
- ``device``  — non-crc device kernels (xxhash scan family)
- ``fused``   — csums emitted by the fused encode+checksum kernel
  (ops/pallas_encode.py) — no standalone checksum pass ran at all

``pallas_fallback`` counts dispatches where the Pallas fold was
enabled on TPU but the shape could not tile (the silent fallback the
round-6 advice flagged).
"""

from __future__ import annotations

_counts: dict[str, int] = {}
_bytes: dict[str, int] = {}
_last: str | None = None
_warned: set[str] = set()


def record(backend: str, nbytes: int = 0) -> None:
    global _last
    _counts[backend] = _counts.get(backend, 0) + 1
    if nbytes:
        _bytes[backend] = _bytes.get(backend, 0) + int(nbytes)
    if not backend.endswith("_fallback"):
        _last = backend


def last_backend() -> str | None:
    """Backend of the most recent checksum computation."""
    return _last


def counts() -> dict[str, int]:
    return dict(_counts)


def bytes_hashed() -> dict[str, int]:
    return dict(_bytes)


def reset() -> None:
    global _last
    _counts.clear()
    _bytes.clear()
    _last = None
    _warned.clear()


def warn_once(key: str, msg: str) -> None:
    """Log a routing surprise exactly once per process (the
    supported()-fell-back case must be visible, not spammy)."""
    if key in _warned:
        return
    _warned.add(key)
    try:
        from ceph_tpu.utils.log import get_logger

        get_logger("checksum").info(msg)
    except Exception:
        pass
