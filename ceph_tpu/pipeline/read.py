"""Read pipeline — the ``ECCommon::ReadPipeline`` analog.

Behavioral mirror of the reference's degraded-read path
(osd/ECCommon.cc: ``get_min_avail_to_read_shards`` :198, ``do_read_op``
:387, ``get_remaining_shards`` retry :312, ``complete_read_op`` :90;
client entry osd/ECBackend.cc ``objects_read_and_reconstruct`` :1725):

1. Plan: if every wanted data shard is available, read exactly the
   wanted extents (fast path, no decode). Otherwise apply the codec's
   ``minimum_to_decode`` (with sub-chunk selectors — the CLAY fractional
   repair plan rides the same ``shard_read_t`` seam, ECCommon.h:83-133)
   over the chunk-aligned window and decode.
2. Dispatch per-shard sub-reads (the ECSubRead fan-out seam).
3. On a shard EIO, retry from the remaining survivors: re-plan with the
   failed shard excluded and issue only the still-missing reads
   (``get_remaining_shards``); if no plan exists, the client gets EIO.
4. Client reads complete strictly in submission order regardless of
   backend completion order (``in_progress_client_reads``,
   ECBackend.h:131-148).

TPU-first delta: reconstruction is one batched device decode over the
whole window (cached inverted generator rows), not a per-slice call.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from collections.abc import Callable
from dataclasses import dataclass, field

import numpy as np

from .extents import ExtentSet
from .shard_map import ShardExtentMap
from .stripe import StripeInfo


class ShardReadError(Exception):
    """A shard store failed a sub-read. ``kind`` distinguishes an IO
    error ("eio") from an absent object ("missing", the ENOENT analog
    of ECInject read type 1) — both retry identically."""

    def __init__(self, shard: int, oid: str = "", kind: str = "eio") -> None:
        super().__init__(f"shard {shard} {kind} on {oid!r}")
        self.shard = shard
        self.kind = kind


@dataclass
class ShardRead:
    """One shard's sub-read: extents plus optional sub-chunk selectors
    (the ``shard_read_t`` analog, ECCommon.h:83-133)."""

    shard: int
    extents: ExtentSet
    subchunks: list[tuple[int, int]] | None = None  # (index, count) runs


def subchunk_byte_extents(
    window: ExtentSet,
    chunk_size: int,
    sub_chunk_count: int,
    subchunks: list[tuple[int, int]],
) -> ExtentSet:
    """Restrict chunk-granular extents to selected sub-chunk byte ranges.

    Each chunk_size-aligned chunk inside ``window`` contributes only the
    (index, count) sub-chunk runs — how ECSubRead's subchunk selectors
    shrink the wire/disk IO for CLAY repair.
    """
    sub = chunk_size // sub_chunk_count
    out = ExtentSet()
    for start, end in window:
        c = (start // chunk_size) * chunk_size
        while c < end:
            for index, count in subchunks:
                lo = max(c + index * sub, start)
                hi = min(c + (index + count) * sub, end)
                if lo < hi:
                    out.insert(lo, hi - lo)
            c += chunk_size
    return out


def get_min_avail_to_read_shards(
    sinfo: StripeInfo,
    codec,
    want: dict[int, ExtentSet],
    avail: set[int],
    costs: dict[int, int] | None = None,
) -> tuple[dict[int, ShardRead], bool]:
    """Choose the shard sub-reads satisfying ``want`` given ``avail``
    (ECCommon.cc:198). Returns (shard_reads, need_decode).

    Fast path: all wanted shards available — read them directly. Slow
    path: available wanted shards still read their own extents, and
    ``minimum_to_decode`` over the MISSING wanted shards picks the
    decode survivors (cost-aware when per-shard ``costs`` are
    supplied); every survivor reads the chunk-aligned window covering
    the wanted extents, narrowed to sub-chunk ranges when the plan
    selects them (the CLAY single-shard repair plan).
    """
    if set(want) <= avail:
        return (
            {s: ShardRead(s, es.copy()) for s, es in want.items() if es},
            False,
        )

    missing = {s for s in want if s not in avail}
    want_raw = {sinfo.get_raw_shard(s) for s in missing}
    avail_raw = {sinfo.get_raw_shard(s) for s in avail}
    if costs is not None:
        chosen = codec.minimum_to_decode_with_cost(
            want_raw, {sinfo.get_raw_shard(s): c for s, c in costs.items()}
        )
        # Re-plan over the cost-chosen survivors so sub-chunk
        # selectors survive cost awareness: a CLAY single-shard
        # repair restricted to the chosen helpers still reads only
        # its repair planes (the cost-aware branch used to flatten
        # every plan to full chunks, silently forfeiting the MSR
        # read savings whenever a caller supplied costs).
        try:
            plan = codec.minimum_to_decode(want_raw, set(chosen))
        except ValueError:
            plan = {
                raw: [(0, codec.get_sub_chunk_count())]
                for raw in chosen
            }
    else:
        plan = codec.minimum_to_decode(want_raw, avail_raw)

    # Chunk-aligned hull of everything wanted, in shard-offset space.
    cs = sinfo.chunk_size
    hull = sinfo.chunk_aligned_hull(want.values())
    if hull is None:
        return {}, False
    window = ExtentSet([hull])

    sub_count = codec.get_sub_chunk_count()
    reads: dict[int, ShardRead] = {}
    for raw, subchunks in plan.items():
        shard = sinfo.get_shard(raw)
        full = [(0, sub_count)]
        if sub_count > 1 and subchunks and list(subchunks) != full:
            extents = subchunk_byte_extents(window, cs, sub_count, subchunks)
            reads[shard] = ShardRead(shard, extents, list(subchunks))
        else:
            reads[shard] = ShardRead(shard, window.copy())
    # Available wanted shards read their own extents on top of any
    # helper role (the client still needs their bytes verbatim).
    for s, es in want.items():
        if s not in avail or not es:
            continue
        if s in reads:
            reads[s].extents.union(es)
        else:
            reads[s] = ShardRead(s, es.copy())
    return reads, True


def gather_ro_range(
    sinfo: StripeInfo, smap: ShardExtentMap, ro_offset: int, length: int
) -> bytes:
    """Assemble the rados byte range from per-shard buffers (the inverse
    of the write path's shard scatter; absent bytes read as zero)."""
    out = np.zeros(length, dtype=np.uint8)
    pos, taken = ro_offset, 0
    while taken < length:
        chunk_index = pos // sinfo.chunk_size
        raw = chunk_index % sinfo.k
        in_chunk = pos % sinfo.chunk_size
        take = min(sinfo.chunk_size - in_chunk, length - taken)
        shard_off = (chunk_index // sinfo.k) * sinfo.chunk_size + in_chunk
        out[taken : taken + take] = smap.get(
            sinfo.get_shard(raw), shard_off, take
        )
        pos += take
        taken += take
    return out.tobytes()


def reconstruct_shards(
    sinfo: StripeInfo,
    codec,
    result: ShardExtentMap,
    want: dict[int, ExtentSet],
    shard_reads: dict[int, ShardRead],
    object_size: int,
    error_shards: frozenset[int] | set[int] = frozenset(),
) -> None:
    """Fill wanted-but-unread shards of ``result`` from its survivors.

    Shared by the client read path and shard recovery: CLAY fractional
    repair when the plan carried sub-chunk selectors and exactly one
    shard is lost, plain windowed decode otherwise.
    """
    lost = set()
    for s, es in want.items():
        got = result.get_extent_set(s)
        if any(not got.contains(a, b - a) for a, b in es):
            lost.add(s)
    if not lost:
        return
    fractional = any(sr.subchunks is not None for sr in shard_reads.values())
    if fractional and len(lost) == 1 and hasattr(codec, "repair"):
        _repair_fractional(
            sinfo, codec, result, want, shard_reads, object_size,
            error_shards, lost,
        )
        return
    result.decode(codec, lost, object_size)


def _repair_fractional(
    sinfo: StripeInfo,
    codec,
    result: ShardExtentMap,
    want: dict[int, ExtentSet],
    shard_reads: dict[int, ShardRead],
    object_size: int,
    error_shards,
    lost: set[int],
) -> None:
    """CLAY fractional repair: per chunk in the window, feed each
    helper's concatenated repair sub-chunks to ``codec.repair``."""
    cs = sinfo.chunk_size
    want_raw = {sinfo.get_raw_shard(s) for s in lost}
    helpers = {
        s: sr for s, sr in shard_reads.items()
        if s not in error_shards and s not in lost
        and sr.subchunks is not None
    }
    # Window = chunk hull of the wanted extents.
    lo, hi = sinfo.chunk_aligned_hull(want.values())
    n_chunks = (hi - lo) // cs
    import jax.numpy as jnp

    chunks_in: dict[int, "jnp.ndarray"] = {}
    for shard, sr in helpers.items():
        rows = []
        for c in range(n_chunks):
            base = lo + c * cs
            sel = subchunk_byte_extents(
                ExtentSet([(base, base + cs)]),
                cs,
                codec.get_sub_chunk_count(),
                sr.subchunks or [(0, codec.get_sub_chunk_count())],
            )
            parts = [result.get(shard, s, e - s) for s, e in sel]
            rows.append(np.concatenate(parts))
        chunks_in[sinfo.get_raw_shard(shard)] = jnp.asarray(np.stack(rows))
    out = codec.repair(want_raw, chunks_in)
    for raw in want_raw:
        shard = sinfo.get_shard(raw)
        buf = np.asarray(out[raw]).reshape(n_chunks * cs)
        shard_size = sinfo.object_size_to_shard_size(object_size, shard)
        end = min(hi, shard_size)
        if end > lo:
            result.insert(shard, lo, buf[: end - lo])


class ClientReadOp:
    """One in-flight client read (ECCommon::ClientAsyncReadStatus +
    read_request_t rolled together)."""

    def __init__(
        self,
        rid: int,
        oid: str,
        ro_offset: int,
        length: int,
        on_complete: Callable[["ClientReadOp"], None] | None,
    ) -> None:
        self.rid = rid
        self.oid = oid
        self.ro_offset = ro_offset
        self.length = length
        self.on_complete = on_complete
        self.want: dict[int, ExtentSet] = {}
        self.shard_reads: dict[int, ShardRead] = {}
        self.need_decode = False
        self.result: ShardExtentMap | None = None
        self.error_shards: set[int] = set()
        # shard -> outstanding sub-read count (a retry can widen a
        # shard's window while its first sub-read is still in flight).
        self.pending: dict[int, int] = {}
        self.done = False
        self.data: bytes | None = None
        self.error: Exception | None = None
        self.t_submit: float | None = None


class ReadPipeline:
    """plan → sub-reads → (decode) → in-order client completion."""

    def __init__(
        self,
        sinfo: StripeInfo,
        codec,
        backend,
        size_fn: Callable[[str], int],
        perf_name: str = "ec_read",
    ) -> None:
        self.sinfo = sinfo
        self.codec = codec
        self.backend = backend
        self.size_fn = size_fn
        self._next_rid = 1
        self._inflight: "OrderedDict[int, ClientReadOp]" = OrderedDict()
        from ceph_tpu.utils import PerfCountersBuilder, perf_collection

        # The io_counters read_cnt/read_bytes analog (ECBackend.cc:
        # 1797-1823) plus reconstruct/retry visibility.
        self.perf = (
            PerfCountersBuilder(perf_collection, perf_name)
            .add_u64_counter("read_ops", "client reads submitted")
            .add_u64_counter("read_bytes", "client bytes returned")
            .add_u64_counter("reconstruct_ops", "reads that decoded")
            .add_u64_counter(
                "helper_read_bytes",
                "bytes requested from shard stores by sub-reads (the "
                "MSR observable: CLAY fractional repair keeps this "
                "below the k-full-chunk bytes a naive decode reads)",
            )
            .add_u64_counter("retries", "sub-read retries after errors")
            .add_u64_counter("errors", "reads failed after retry")
            .add_avg("read_lat", "submit-to-complete seconds")
            .create_perf_counters()
        )

    # -- client entry (objects_read_and_reconstruct analog) ------------
    def submit(
        self,
        oid: str,
        ro_offset: int,
        length: int,
        on_complete: Callable[[ClientReadOp], None] | None = None,
    ) -> int:
        op = ClientReadOp(self._next_rid, oid, ro_offset, length, on_complete)
        op.t_submit = time.perf_counter()
        self._next_rid += 1
        self._inflight[op.rid] = op
        self.perf.inc("read_ops")

        # Reads past EOF are trimmed (objects_read_sync semantics).
        size = self.size_fn(oid)
        if ro_offset >= size:
            op.length = 0
        else:
            op.length = min(length, size - ro_offset)
        if op.length <= 0:
            op.data = b""
            self._finish(op)
            return op.rid

        op.want = self.sinfo.ro_range_to_shard_extent_set(
            op.ro_offset, op.length
        )
        op.result = ShardExtentMap(self.sinfo)
        try:
            op.shard_reads, op.need_decode = get_min_avail_to_read_shards(
                self.sinfo, self.codec, op.want, self._avail()
            )
        except ValueError as e:
            op.error = e
            self._finish(op)
            return op.rid
        self._issue(op, op.shard_reads)
        return op.rid

    def read_sync(self, oid: str, ro_offset: int, length: int) -> bytes:
        """Synchronous wrapper (ECBackend::objects_read_sync analog).
        Backends with a ``drain_until`` event loop (the networked one)
        are drained on this thread until the read completes."""
        out: dict[str, ClientReadOp] = {}
        self.submit(oid, ro_offset, length, lambda op: out.update(op=op))
        drain = getattr(self.backend, "drain_until", None)
        if drain is not None and "op" not in out:
            drain(lambda: "op" in out)
        op = out["op"]
        if op.error is not None:
            raise op.error
        return op.data

    # -- internals ------------------------------------------------------
    def _avail(self) -> set[int]:
        return self.backend.avail_shards()

    def _issue(self, op: ClientReadOp, reads: dict[int, ShardRead]) -> None:
        for shard in reads:
            op.pending[shard] = op.pending.get(shard, 0) + 1
        self.perf.inc(
            "helper_read_bytes",
            sum(
                end - start
                for sr in reads.values()
                for start, end in sr.extents
            ),
        )
        for sr in list(reads.values()):
            self.backend.read_shard_async(
                sr.shard,
                op.oid,
                sr.extents,
                lambda shard, result, _op=op: self._sub_read_done(
                    _op, shard, result
                ),
            )

    def _sub_read_done(self, op: ClientReadOp, shard: int, result) -> None:
        left = op.pending.get(shard, 0) - 1
        if left > 0:
            op.pending[shard] = left
        else:
            op.pending.pop(shard, None)
        if isinstance(result, Exception):
            op.error_shards.add(shard)
            self._retry(op)
        else:
            for start, buf in result.items():
                op.result.insert(shard, start, buf)
            if not op.pending:
                self._complete(op)

    def _retry(self, op: ClientReadOp) -> None:
        """Re-plan from the remaining survivors (get_remaining_shards,
        ECCommon.cc:312): issue only byte ranges not already read or
        requested. A still-pending shard can be widened — the extra
        sub-read just bumps its pending count."""
        self.perf.inc("retries")
        avail = self._avail() - op.error_shards
        try:
            reads, need_decode = get_min_avail_to_read_shards(
                self.sinfo, self.codec, op.want, avail
            )
        except ValueError as e:
            op.error = e
            if not op.pending:
                self._complete(op)
            return
        op.need_decode = op.need_decode or need_decode
        fresh: dict[int, ShardRead] = {}
        for shard, sr in reads.items():
            if shard in op.error_shards:
                continue
            already = op.result.get_extent_set(shard)
            prior = op.shard_reads.get(shard)
            if prior is not None:
                already = already.copy()
                already.union(prior.extents)
            missing = sr.extents.difference(already)
            if missing:
                fresh[shard] = ShardRead(shard, missing, sr.subchunks)
        # Refresh the sub-chunk selectors to the CURRENT plan: a retry
        # that fell back from fractional repair to full decode must not
        # leave stale selectors steering _reconstruct into codec.repair
        # with too few helpers.
        for shard, sr in op.shard_reads.items():
            new = reads.get(shard)
            sr.subchunks = new.subchunks if new is not None else None
        for shard, sr in fresh.items():
            if shard in op.shard_reads:
                op.shard_reads[shard].extents.union(sr.extents)
            else:
                op.shard_reads[shard] = ShardRead(
                    shard, sr.extents.copy(), sr.subchunks
                )
        if fresh:
            self._issue(op, fresh)
        elif not op.pending:
            self._complete(op)

    def _complete(self, op: ClientReadOp) -> None:
        if op.error is None and op.need_decode:
            from ceph_tpu.utils import tracer

            self.perf.inc("reconstruct_ops")
            try:
                with tracer.span("ec_reconstruct", oid=op.oid, rid=op.rid):
                    self._reconstruct(op)
            except ValueError as e:
                op.error = e
        if op.error is None:
            op.data = gather_ro_range(
                self.sinfo, op.result, op.ro_offset, op.length
            )
            self.perf.inc("read_bytes", len(op.data))
        else:
            self.perf.inc("errors")
        self._finish(op)

    def _reconstruct(self, op: ClientReadOp) -> None:
        """Decode missing wanted shards from the survivors in
        ``op.result`` (complete_read_op → shard_extent_map_t::decode)."""
        reconstruct_shards(
            self.sinfo,
            self.codec,
            op.result,
            op.want,
            op.shard_reads,
            self.size_fn(op.oid),
            op.error_shards,
        )

    def _finish(self, op: ClientReadOp) -> None:
        """In-order completion (in_progress_client_reads semantics)."""
        op.done = True
        while self._inflight:
            rid, front = next(iter(self._inflight.items()))
            if not front.done:
                return
            self._inflight.pop(rid)
            if front.t_submit is not None:
                self.perf.ainc(
                    "read_lat", time.perf_counter() - front.t_submit
                )
            if front.on_complete is not None:
                front.on_complete(front)
