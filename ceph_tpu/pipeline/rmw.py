"""The RMW (read-modify-write) pipeline — ``RMWPipeline`` +
``ECTransaction`` analog.

Behavioral mirror of the reference write path
(osd/ECCommon.cc:649 ``start_rmw`` → ECExtentCache → ``cache_ready`` →
``Op::generate_transactions`` → osd/ECTransaction.cc:916 → per-shard
sub-writes → in-order commit via ``waiting_commit``,
ECCommon.h:553-555):

1. ``WritePlan`` (ECTransaction.h:62-64): choose full-stripe re-encode
   vs parity-delta per codec flags and read cost, and compute the
   shard extents that must be fetched before encoding.
2. The extent cache satisfies reads (hit) or issues ONE backend read.
3. On cache-ready, the encode runs — ``ShardExtentMap.encode`` or
   ``encode_parity_delta`` (the device dispatch) — and per-shard
   ``Transaction``s are generated, including the ``hinfo_key`` attr
   update (ECTransaction.cc:497,902; attr name ECUtil.cc:1179).
4. Sub-writes dispatch to every shard's store; client commit callbacks
   fire strictly in tid order no matter the ack order.

TPU-first deltas: the encode is one batched device dispatch per op
(not per 4K slice), and the whole pipeline is an event-driven state
machine a host thread drives between device batches — no per-op
threads, mirroring crimson's run-to-completion stance more than the
classic OSD's thread pools.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from collections.abc import Callable
from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.codecs.interface import Flag
from ceph_tpu.store import Transaction
from ceph_tpu.utils.crash_points import crash_points
from ceph_tpu.utils.optracker import NULL_OP, op_tracker

from .extent_cache import CacheOp, ECExtentCache
from .extents import ExtentSet
from .hashinfo import HashInfo
from .shard_map import ShardExtentMap
from .stripe import StripeInfo
from ceph_tpu.utils.lockdep import DebugRLock

HINFO_KEY = "hinfo_key"  # ECUtil.cc:1179
#: object-info attr: the rados object size travels with every shard
#: txn (the object_info_t "_" attr role) so a NEW primary can recover
#: sizes after failover instead of trusting in-memory state.
OI_KEY = "oi"


def pack_oi(size: int, eversion: tuple[int, int] = (0, 0)) -> bytes:
    """object_info_t attr payload: ro size + last-write eversion.

    The eversion is the reference's ``eversion_t`` (osd_types.h) —
    (map epoch, op version) stamped atomically with every sub-write,
    so peering can tell a shard whose content matches authoritative
    history from one that diverged (a partitioned ex-primary's
    locally-applied writes)."""
    return f"{size}:{eversion[0]}:{eversion[1]}".encode()


def parse_oi(raw: bytes) -> tuple[int, tuple[int, int]]:
    """(size, eversion); bare-size payloads (pre-eversion format)
    parse with the null eversion (0, 0) = 'unknown'. Any other shape
    is corrupt and raises ValueError (the error every caller already
    handles)."""
    parts = raw.decode().split(":")
    if len(parts) == 1:
        return int(parts[0]), (0, 0)
    if len(parts) != 3:
        raise ValueError(f"corrupt OI payload: {raw!r}")
    return int(parts[0]), (int(parts[1]), int(parts[2]))
#: shard-index attr: which logical EC shard these bytes are. Read
#: paths compare it against the position they are asking for, so a
#: CRUSH remap can never silently serve shard j's bytes as shard i
#: (misplaced data reads as a clean error until backfill moves it).
SI_KEY = "si"


@dataclass
class WritePlan:
    """What one write op will read and write, and via which strategy
    (the ECTransaction.h:62-64 ``WritePlan{want_read, plans}`` analog)."""

    do_parity_delta: bool
    to_read: dict[int, ExtentSet] = field(default_factory=dict)
    to_write: dict[int, ExtentSet] = field(default_factory=dict)

    def read_bytes(self) -> int:
        return sum(es.size() for es in self.to_read.values())


def plan_write(
    sinfo: StripeInfo,
    flags: Flag,
    ro_offset: int,
    length: int,
    object_size: int,
) -> WritePlan:
    """Choose the write strategy (ECTransaction.cc:77-79 decision).

    Costs, in bytes read from the backend:
    - full-stripe: the UNWRITTEN data-shard extents of every touched
      stripe (so parity can be re-encoded from complete stripes);
    - parity-delta: the OLD values of written data extents plus the
      old parity extents (delta = old XOR new; parity' = parity XOR
      G·delta).
    Parity-delta additionally requires the codec's
    PARITY_DELTA_OPTIMIZATION flag (jerasure matrix/ISA families).
    Reads beyond current object size are elided (absent bytes are
    zero by the zero-padding convention).
    """
    touched = sinfo.ro_range_to_shard_extent_set(ro_offset, length, parity=True)
    to_write = {s: es.align(4096) for s, es in touched.items()}
    if flags & Flag.PARITY_DELTA_CHUNK_GRANULARITY:
        # packet-layout codes scatter a sub-chunk write's parity
        # update across the whole chunk: parity reads/writes must
        # cover whole chunks so the delta driver can hand the codec
        # chunk-shaped windows with the old parity present. Align to
        # the CHUNK, exactly the widening encode_parity_delta applies
        # — max(chunk, page) only coincides with chunk boundaries
        # when chunk is a page multiple (a sub-page liberation chunk
        # like 1792 would leave the widened window's old parity
        # unread and zero-filled: silent corruption).
        to_write = {
            s: (
                es.align(sinfo.chunk_size)
                if sinfo.is_parity_shard(s) else es
            )
            for s, es in to_write.items()
        }

    def clip_to_stored(shard: int, es: ExtentSet) -> ExtentSet:
        stored = sinfo.object_size_to_shard_size(object_size, shard)
        out = ExtentSet()
        for s, e in es:
            if s < stored:
                out.insert(s, min(e, stored) - s)
        return out

    # Subtract only the bytes the client actually overwrites (the
    # UNALIGNED extents): a sub-page boundary still needs its old bytes
    # read so the re-encode and the page write both see them — aligned
    # extents here once dropped boundary bytes, encoding zeros into
    # parity while the store kept the old data (silent corruption).
    data_written = {
        s: es for s, es in touched.items() if sinfo.is_data_shard(s)
    }

    # Full-stripe read set: the PAGE window of the write minus what we
    # overwrite. The window must be the page-aligned to_write hull, not
    # the chunk hull: the encode pads to pages, so a parity page covers
    # every stripe inside it — with chunk_size < page that reaches
    # stripes the chunk hull misses, and encoding them without their
    # old data would zero them into parity (silent corruption).
    full_read: dict[int, ExtentSet] = {}
    lo = min(es.range_start() for es in to_write.values())
    hi = max(es.range_end() for es in to_write.values())
    for raw in range(sinfo.k):
        shard = sinfo.get_shard(raw)
        hull = ExtentSet([(lo, hi)])
        need = hull.difference(data_written.get(shard, ExtentSet()))
        need = clip_to_stored(shard, need)
        if need:
            full_read[shard] = need

    # Parity-delta read set: old data under the written extents + parity.
    delta_read: dict[int, ExtentSet] = {}
    for shard, es in to_write.items():
        need = clip_to_stored(shard, es)
        if need:
            delta_read[shard] = need

    full = WritePlan(False, full_read, to_write)
    if not (flags & Flag.PARITY_DELTA_OPTIMIZATION):
        return full
    delta = WritePlan(True, delta_read, to_write)
    # Nothing stored yet -> both read nothing; full-stripe encode is the
    # degenerate winner (no old parity to delta against).
    if not delta_read or all(
        sinfo.is_parity_shard(s) and not clip_to_stored(s, es)
        for s, es in delta_read.items()
    ):
        return full
    # tie goes to delta: it touches only the written chunks' pages,
    # where full-stripe re-encode rewrites every parity page
    return delta if delta.read_bytes() <= full.read_bytes() else full


class ClientOp:
    """One in-flight client write (the RMWPipeline::Op analog)."""

    def __init__(
        self,
        tid: int,
        oid: str,
        ro_offset: int,
        data: bytes,
        on_commit: Callable[["ClientOp"], None] | None,
    ) -> None:
        self.tid = tid
        self.oid = oid
        self.ro_offset = ro_offset
        self.data = data
        self.on_commit = on_commit
        self.plan: WritePlan | None = None
        self.cache_op: CacheOp | None = None
        self.pending_shards: set[int] = set()
        self.acked_shards: set[int] = set()
        self.extra_attrs: "dict[str, bytes] | None" = None
        self.written: "ShardExtentMap | None" = None
        self.committed = False
        self.notified = False
        self.error: Exception | None = None
        self.t_submit: float | None = None
        #: live-op handle (dump_ops_in_flight): queued -> dispatched
        #: -> waiting_for_subops -> committed -> done
        self.tracked = NULL_OP


class ShardBackend:
    """Dispatch boundary for per-shard sub-ops (the MOSDECSubOpWrite/
    Read fan-out seam). The local implementation writes straight into
    per-shard MemStores; the distributed layer substitutes messengers.

    ``defer_acks``/``defer_reads``: tests set these to capture callbacks
    and release them out of order, exercising the in-order queues.
    ``down_shards``/``fail_read_shards``: availability + EIO injection
    (the ECInject seam — reads from those shards error).
    """

    def __init__(self, stores: dict[int, "object"]) -> None:
        self.stores = stores
        self.defer_acks = False
        self.deferred: list[tuple[int, Callable[[], None]]] = []
        self.down_shards: set[int] = set()
        self.fail_read_shards: set[int] = set()
        self.defer_reads = False
        self.deferred_reads: list[tuple[int, Callable[[], None]]] = []

    def avail_shards(self) -> set[int]:
        """Shards the read planner may target (acting-set analog)."""
        return set(self.stores) - self.down_shards

    def read_shard_async(
        self,
        shard: int,
        oid: str,
        extents: ExtentSet,
        cb: Callable[[int, "dict[int, bytes] | Exception"], None],
    ) -> None:
        """Sub-read fan-out seam (ECSubRead → handle_sub_read). Calls
        ``cb(shard, {offset: bytes})`` or ``cb(shard, ShardReadError)``.
        Consults the ECInject registry the way handle_sub_read does."""
        from .inject import ec_inject
        from .read import ShardReadError

        def run() -> None:
            if shard in self.fail_read_shards or shard in self.down_shards:
                cb(shard, ShardReadError(shard, oid))
            elif ec_inject.test_read_error0(oid, shard):
                cb(shard, ShardReadError(shard, oid, kind="eio"))
            elif ec_inject.test_read_error1(oid, shard):
                cb(shard, ShardReadError(shard, oid, kind="missing"))
            else:
                try:
                    cb(shard, self.read_shard(shard, oid, extents))
                except Exception:
                    # store-level EIO (e.g. a BlockStore csum failure)
                    # answers as a shard error — the reference's
                    # handle_sub_read returns -EIO, it never tears the
                    # connection down (ECBackend.cc:998)
                    cb(shard, ShardReadError(shard, oid, kind="eio"))

        if self.defer_reads:
            self.deferred_reads.append((shard, run))
        else:
            run()

    def release_deferred_reads(self, order: list[int] | None = None) -> None:
        pending = self.deferred_reads
        self.deferred_reads = []
        if order is not None:
            pending = sorted(
                pending, key=lambda t: order.index(t[0]) if t[0] in order else 99
            )
        for _, run in pending:
            run()

    def read_shard(self, shard: int, oid: str, extents: ExtentSet) -> dict[int, bytes]:
        from .inject import ec_inject

        store = self.stores[shard]
        out = {}
        for start, end in extents:
            try:
                buf = store.read(oid, start, end - start)
            except FileNotFoundError:
                buf = b""
            buf = buf + b"\0" * (end - start - len(buf))  # zero-pad EOF
            out[start] = buf
        if ec_inject.test_read_error2(oid, shard):
            # ECInject read type 2: the payload leaves here silently
            # corrupted — only an integrity tier may notice
            out = {
                start: ec_inject.corrupt(buf)
                for start, buf in out.items()
            }
        return out

    def submit_shard_txn(
        self, shard: int, txn: Transaction, ack: Callable[[], None]
    ) -> None:
        from .inject import ec_inject

        oid = txn.oids()[0] if txn.oids() else ""
        if ec_inject.test_write_error3(oid, exact=True):
            # ECInject write type 3: the receiving OSD aborts in
            # handle_sub_write (ECBackend.cc:922-926). In-process
            # analog: the shard's OSD dies — nothing applies, no ack,
            # and the shard drops out of the acting set. Exact-oid
            # consult: at the daemon tier this hop sees per-shard
            # store keys and the daemon already consulted the rule
            # under the base oid — matching here too would decrement
            # when/duration twice per op.
            self.down_shards.add(shard)
            return
        if ec_inject.test_write_error1(oid, shard):
            return  # sub-write silently dropped: ack never arrives
        self.stores[shard].queue_transactions(txn)
        if self.defer_acks:
            self.deferred.append((shard, ack))
        else:
            ack()

    def release_deferred(self, order: list[int] | None = None) -> None:
        pending = self.deferred
        self.deferred = []
        if order is not None:
            pending = sorted(
                pending, key=lambda t: order.index(t[0]) if t[0] in order else 99
            )
        for _, ack in pending:
            ack()


class RMWPipeline:
    """start_rmw → cache → encode → sub-writes → in-order commit."""

    def __init__(
        self,
        sinfo: StripeInfo,
        codec,
        backend: ShardBackend,
        cache_lines: int | None = None,
        perf_name: str = "ec_rmw",
        pglog=None,
    ) -> None:
        self.pglog = pglog
        self.sinfo = sinfo
        self.codec = codec
        self.backend = backend
        #: csum-block granularity for the fused encode+checksum path
        #: (matches the stores' BlueStore-analog default); the encode
        #: dispatch emits per-block crc32c for all k+m shards at this
        #: granularity and sub-writes carry them to the stores
        from ceph_tpu.utils import config as _config

        self.csum_block = int(_config.get("csum_block_size"))
        if cache_lines is None:
            from ceph_tpu.utils import config

            cache_lines = config.get("ec_extent_cache_lines")
        self.cache = ECExtentCache(sinfo, self._backend_read, cache_lines)
        self._next_tid = 1
        self._inflight: "OrderedDict[int, ClientOp]" = OrderedDict()
        self._object_sizes: dict[str, int] = {}
        #: size as of the LAST SUBMITTED op (dispatch updates
        #: _object_sizes later): decisions made at submit time about
        #: a racing in-flight op's outcome — the truncate boundary
        #: re-encode — must use the projected view, not the
        #: dispatch-time one
        self._projected_sizes: dict[str, int] = {}
        self._hinfo: dict[str, HashInfo] = {}
        #: current map epoch, stamped (with the op tid) into every
        #: write's OI attr as the object's eversion; the owning daemon
        #: refreshes it on map change
        self.epoch = 0
        self._eversions: dict[str, tuple[int, int]] = {}
        #: stamps recorded by writes THIS pipeline instance performed
        #: (never seeded from stored attrs): the only eversions strong
        #: enough to anchor a scrub election — a cold-boot attr may
        #: itself be divergent
        self._live_eversions: dict[str, tuple[int, int]] = {}
        #: oid -> backend-read failure awaiting its op (degraded RMW
        #: read failed; the op aborts in _cache_ready, in order)
        self._read_errors: dict[str, Exception] = {}
        #: ECInject write-type-2 seam: the owning daemon points this at
        #: its "mark me down" mon command (ECBackend.cc:1158-1167);
        #: standalone pipelines leave it None
        self.on_osd_down_inject: Callable[[], None] | None = None
        #: the owning OSD daemon (None for standalone pipelines) —
        #: crash points fire with it so osd= filters and the ``kill``
        #: action resolve; never otherwise consulted
        self.owner = None
        #: serializes ack/commit bookkeeping: sub-write acks arrive on
        #: messenger pump threads while map changes release dead
        #: shards' acks from the monitor-notify thread — both mutate
        #: pending_shards/_inflight. Reentrant: a local synchronous
        #: dispatch acks inside submit, and on_commit may re-enter.
        self._ack_lock = DebugRLock("rmw.ack")
        from ceph_tpu.utils import PerfCountersBuilder, perf_collection

        self.perf = (
            PerfCountersBuilder(perf_collection, perf_name)
            .add_u64_counter("write_ops", "client writes submitted")
            .add_u64_counter("write_bytes", "client bytes written")
            .add_u64_counter("parity_delta_ops", "writes via parity delta")
            .add_u64_counter("full_stripe_ops", "writes via full re-encode")
            .add_u64_counter("aborts", "writes failed before dispatch")
            .add_avg("commit_lat", "submit-to-commit seconds")
            .create_perf_counters()
        )

    def on_interval_change(self) -> None:
        """Drop every in-memory projection of object state (sizes,
        eversions, hinfo, cached extents) — PG::on_change. While this
        daemon was NOT the serving primary, its STORE advanced through
        the replica sub-write role, which never updates these caches:
        a re-elected ex-primary serving from them computed append
        offsets from its last primacy's sizes and tore the log the
        interim primary had extended (round-5 kill/revive thrash
        find). The next op re-primes from the store's OI/HashInfo
        attrs.

        In-flight ops of the OLD interval are REQUEUED-as-errors (the
        reference requeues them into the new interval and the client
        resend dedups via reqid): their sub-writes are fenced at the
        members — `committed=False`, no ack ever — so leaving them
        parked wedges the per-object cache FIFO, and every new-interval
        op on the object queues behind the corpse forever (the
        kill × net_flaky composition found the wedge: a live,
        re-elected primary kept its own fenced op parked, stalling the
        coalesce drain for the whole worker). Completing them with the
        retryable interval error releases the cache; the resend
        re-runs them against the new interval's election."""
        stale: list[ClientOp] = []
        with self._ack_lock:
            self._object_sizes.clear()
            self._projected_sizes.clear()
            self._eversions.clear()
            self._live_eversions.clear()
            self._hinfo.clear()
            for op in self._inflight.values():
                if not op.committed and op.written is not None:
                    # dispatched (sub-writes on the wire, fenceable);
                    # un-dispatched ops still ride the cache queue and
                    # will dispatch -> fence -> ... so requeue them on
                    # their dispatch instead: leave them be
                    op.error = IOError(
                        "interval changed - op requeued for resend"
                    )
                    op.committed = True
                    op.tracked.mark_event("interval_fenced")
                    self.perf.inc("aborts")
                    stale.append(op)
            self.cache.on_change()
        # cache release outside the lock (the write_done may cascade);
        # a requeued op publishes an EMPTY map like any failed op
        for op in stale:
            self.cache.write_done(op.cache_op, ShardExtentMap(self.sinfo))
        with self._ack_lock:
            self._check_commit_order()

    def _track(self, op: ClientOp, kind: str) -> None:
        """Register the op with the live tracker under the OWNING
        daemon's name (pipeline-grade perf names collapse to osd.N);
        the commit-order pop finishes it."""
        op.tracked = op_tracker.register(
            kind,
            daemon=(
                f"osd.{self.owner.osd_id}" if self.owner is not None
                else self.perf.name
            ),
            oid=op.oid, tid=op.tid,
        )
        op.tracked.mark_event("queued")

    # -- client entry (ECBackend::submit_transaction analog) -----------
    def submit(
        self,
        oid: str,
        ro_offset: int,
        data: bytes,
        on_commit: Callable[[ClientOp], None] | None = None,
        extra_attrs: "dict[str, bytes] | None" = None,
    ) -> int:
        """``extra_attrs`` ride every shard txn of this op (the
        daemon's replicated reqid-dedup window travels here, so a
        resend after primary failover can be replayed instead of
        re-applied — the pg-log reqid role)."""
        op = ClientOp(self._next_tid, oid, ro_offset, bytes(data), on_commit)
        op.extra_attrs = dict(extra_attrs) if extra_attrs else None
        op.t_submit = time.perf_counter()
        self._next_tid += 1
        self._inflight[op.tid] = op
        self._track(op, "rmw_write")
        self.perf.inc("write_ops")
        self.perf.inc("write_bytes", len(data))

        if not data:
            # Zero-length write: a no-op that still commits in order
            # (plan_write has no extents to plan over).
            op.committed = True
            self._check_commit_order()
            return op.tid

        from .inject import ec_inject

        if ec_inject.test_write_error0(oid):
            # Injected client-write abort (ECInject write type 0): the
            # op completes in order with an error, nothing dispatches.
            op.error = IOError(f"injected write error on {oid!r}")
            op.committed = True
            self.perf.inc("aborts")
            self._check_commit_order()
            return op.tid

        from ceph_tpu.utils import tracer

        self._projected_sizes[oid] = max(
            self._projected_sizes.get(
                oid, self._object_sizes.get(oid, 0)
            ),
            ro_offset + len(data),
        )
        with tracer.span("ec_write", oid=oid, tid=op.tid, bytes=len(data)):
            object_size = self._object_sizes.get(oid, 0)
            op.plan = plan_write(
                self.sinfo,
                self.codec.get_flags(),
                ro_offset,
                len(data),
                object_size,
            )
            self.perf.inc(
                "parity_delta_ops" if op.plan.do_parity_delta
                else "full_stripe_ops"
            )
            op.cache_op = self.cache.prepare(
                oid,
                op.plan.to_read,
                op.plan.to_write,
                object_size,
                lambda cop, _op=op: self._cache_ready(_op),
            )
            self.cache.execute([op.cache_op])
        return op.tid

    def submit_remove(
        self,
        oid: str,
        on_commit: Callable[[ClientOp], None] | None = None,
    ) -> int:
        """Whole-object remove, ordered through the same per-object
        cache FIFO as writes (a remove racing an in-flight write must
        apply after it) and journaled in the pg log so a down shard
        cannot resurrect the object on recovery."""
        self._projected_sizes.pop(oid, None)
        op = ClientOp(self._next_tid, oid, 0, b"", on_commit)
        op.t_submit = time.perf_counter()
        self._next_tid += 1
        self._inflight[op.tid] = op
        self._track(op, "rmw_remove")

        def dispatch(cop, _op=op) -> None:
            try:
                live = set(self.backend.avail_shards())
                if self.pglog is not None:
                    self.pglog.append_delete(_op.tid, oid)
                _op.tracked.mark_event(
                    "waiting_for_subops", n=len(live)
                )
                _op.pending_shards = set(live)
                _op.written = ShardExtentMap(self.sinfo)
                self._object_sizes.pop(oid, None)
                self._hinfo.pop(oid, None)
                self._eversions.pop(oid, None)
                self._live_eversions.pop(oid, None)
                for shard in sorted(live):
                    # touch+remove: no-op on shards that never got the
                    # object (a hole at write time)
                    self.backend.submit_shard_txn(
                        shard,
                        Transaction().touch(oid).remove(oid),
                        lambda s=shard, o=_op: self._shard_ack(o, s),
                    )
            except Exception as e:
                self._abort_op(_op, e)

        op.cache_op = self.cache.prepare(oid, {}, {}, 0, dispatch)
        self.cache.execute([op.cache_op])
        return op.tid

    def submit_truncate(
        self,
        oid: str,
        new_size: int,
        on_commit: Callable[[ClientOp], None] | None = None,
        extra_attrs: "dict[str, bytes] | None" = None,
    ) -> int:
        """rados_trunc: resize the object, ordered through the
        per-object cache FIFO like writes. Shrink cuts every shard at
        its exact size (the zero-padding convention must be REAL: a
        later extend-write elides reads past the recorded size, so
        stale tail bytes would silently corrupt parity) and clears the
        cumulative HashInfo like an overwrite; grow just raises the
        recorded size — the gap reads as zeros, rados' hole
        semantics. The pg log journals the cut region so a down shard
        replays it (survivors decode the zero-padded tail to zeros).

        A ragged shrink first writes ZEROS over the boundary stripe's
        tail through the normal RMW path: parity still encodes the
        old bytes there, and cutting the data shards without
        re-encoding would leave the stripe inconsistent (a degraded
        read would decode the pre-truncate content back to life)."""
        old_size_now = self._projected_sizes.get(
            oid, self._object_sizes.get(oid, 0)
        )
        if new_size < old_size_now:
            sw = self.sinfo.stripe_width
            boundary_end = min(-(-new_size // sw) * sw, old_size_now)
            if boundary_end > new_size:
                self.submit(
                    oid, new_size, b"\0" * (boundary_end - new_size)
                )
        # the projection lands AFTER the boundary zero-write's own
        # submit raised it — the post-truncate size is the cut
        self._projected_sizes[oid] = new_size
        op = ClientOp(self._next_tid, oid, 0, b"", on_commit)
        op.t_submit = time.perf_counter()
        self._next_tid += 1
        self._inflight[op.tid] = op
        self._track(op, "rmw_truncate")
        sinfo = self.sinfo

        def dispatch(cop, _op=op) -> None:
            try:
                live = set(self.backend.avail_shards())
                if len(live) < sinfo.k:
                    raise IOError(
                        f"only {len(live)} shards available, need {sinfo.k}"
                    )
                old_size = self._object_sizes.get(oid, 0)
                self._object_sizes[oid] = new_size
                ev = (self.epoch, _op.tid)
                self._eversions[oid] = ev
                self._live_eversions[oid] = ev
                hinfo = self._get_hinfo(oid)
                if new_size < old_size:
                    hinfo.clear()
                hinfo_bytes = hinfo.to_bytes()
                cut: dict[int, ExtentSet] = {}
                txns: list[tuple[int, Transaction]] = []
                for raw in range(sinfo.k + sinfo.m):
                    shard = sinfo.get_shard(raw)
                    new_exact = sinfo.object_size_to_exact_shard_size(
                        new_size, shard
                    )
                    old_exact = sinfo.object_size_to_exact_shard_size(
                        old_size, shard
                    )
                    if old_exact > new_exact:
                        cut[shard] = ExtentSet(
                            [(new_exact, old_exact)]
                        )
                    txn = self._stamp_identity(
                        Transaction().touch(oid).truncate(oid, new_exact),
                        oid, shard, new_size, ev, hinfo_bytes,
                        extra_attrs,
                    )
                    txns.append((shard, txn))
                if self.pglog is not None:
                    # identity attrs journal WITH the cut: a shard
                    # down for a grow (cut == {}) still replays the
                    # new size, or a later takeover on it would clip
                    # the object back to the pre-truncate length
                    self.pglog.append(
                        _op.tid, oid, cut, epoch=self.epoch,
                        xattrs=self._journal_attrs(
                            new_size, ev, hinfo_bytes, extra_attrs
                        ),
                    )
                # stale tail content must leave the cache before any
                # later op snapshots it
                self.cache.invalidate_object(oid)
                _op.tracked.mark_event(
                    "waiting_for_subops", n=len(live)
                )
                _op.pending_shards = set(live)
                _op.written = ShardExtentMap(sinfo)
                for shard, txn in txns:
                    if shard not in live:
                        continue  # hole: journaled; recovered later
                    self.backend.submit_shard_txn(
                        shard, txn,
                        lambda s=shard, o=_op: self._shard_ack(o, s),
                    )
            except Exception as e:
                self._abort_op(_op, e)

        op.cache_op = self.cache.prepare(oid, {}, {}, 0, dispatch)
        self.cache.execute([op.cache_op])
        return op.tid

    def submit_attr_updates(
        self,
        oid: str,
        updates: "dict[str, bytes | None]",
        on_commit: Callable[[ClientOp], None] | None = None,
    ) -> int:
        """Replicated-attr mutations (value None = remove), ordered
        through the per-object cache FIFO like writes/removes and
        journaled in the pg log so a down shard replays them on
        return. Keys are FULL attr names (callers prefix: ``u:`` for
        user xattrs, ``m:`` for omap entries) so identity attrs never
        collide and one batch may mix namespaces."""
        op = ClientOp(self._next_tid, oid, 0, b"", on_commit)
        op.t_submit = time.perf_counter()
        self._next_tid += 1
        self._inflight[op.tid] = op
        self._track(op, "rmw_attrs")
        updates = dict(updates)

        def dispatch(cop, _op=op) -> None:
            try:
                live = set(self.backend.avail_shards())
                if self.pglog is not None:
                    self.pglog.append_xattrs(_op.tid, oid, updates)
                _op.tracked.mark_event(
                    "waiting_for_subops", n=len(live)
                )
                _op.pending_shards = set(live)
                _op.written = ShardExtentMap(self.sinfo)
                for shard in sorted(live):
                    txn = Transaction().touch(oid)
                    for key, value in sorted(updates.items()):
                        if value is None:
                            txn.rmattr(oid, key, ignore_missing=True)
                        else:
                            txn.setattr(oid, key, value)
                    self.backend.submit_shard_txn(
                        shard, txn,
                        lambda s=shard, o=_op: self._shard_ack(o, s),
                    )
            except Exception as e:
                self._abort_op(_op, e)

        op.cache_op = self.cache.prepare(oid, {}, {}, 0, dispatch)
        self.cache.execute([op.cache_op])
        return op.tid

    def submit_setxattr(
        self,
        oid: str,
        name: str,
        value: "bytes | None",
        on_commit: Callable[[ClientOp], None] | None = None,
    ) -> int:
        """User-xattr mutation (the ``u:`` namespace convenience)."""
        return self.submit_attr_updates(
            oid, {"u:" + name: value}, on_commit
        )

    def object_size(self, oid: str) -> int:
        return self._object_sizes.get(oid, 0)

    def forget_object(self, oid: str) -> None:
        """Drop all in-memory per-object state — the peering
        divergent-create removal path: the object never existed in
        authoritative history, so no trace of the divergent stamps
        may survive to answer later authority lookups."""
        self._object_sizes.pop(oid, None)
        self._hinfo.pop(oid, None)
        self._eversions.pop(oid, None)
        self._live_eversions.pop(oid, None)

    def object_eversion(self, oid: str) -> tuple[int, int] | None:
        """Last known (epoch, tid) stamp — may come from a stored
        attr (prime_object); use live_eversion when trust matters."""
        return self._eversions.get(oid)

    def live_eversion(self, oid: str) -> tuple[int, int] | None:
        """(epoch, tid) of a write THIS pipeline performed; None for
        state only known from stored attrs."""
        return self._live_eversions.get(oid)

    def prime_object(
        self, oid: str, size: int, hinfo: HashInfo | None = None,
        eversion: tuple[int, int] | None = None,
    ) -> None:
        """Seed per-object state recovered from stored attrs (OI_KEY /
        HINFO_KEY) — the new-primary takeover path: a freshly elected
        primary must not assume unknown objects are empty."""
        self._object_sizes[oid] = size
        if hinfo is not None:
            self._hinfo[oid] = hinfo
        if eversion is not None and eversion != (0, 0):
            self._eversions[oid] = eversion

    def hinfo(self, oid: str) -> HashInfo | None:
        return self._hinfo.get(oid)

    # -- pipeline stages ------------------------------------------------
    def _backend_read(self, oid: str, want: dict[int, ExtentSet]) -> None:
        """Fetch old data for an RMW. When a wanted shard is down its
        old bytes are reconstructed from a MINIMAL survivor set — the
        same planner + decode the degraded client read uses
        (get_min_avail_to_read_shards / objects_read_and_reconstruct,
        osd/ECBackend.cc:1725). Failures never propagate: the error is
        parked for ``_cache_ready`` to abort the op in order."""
        from .read import get_min_avail_to_read_shards

        smap = ShardExtentMap(self.sinfo)
        try:
            avail = set(self.backend.avail_shards())
            holes = {s for s in want if s not in avail}
            reads, need_decode = get_min_avail_to_read_shards(
                self.sinfo, self.codec, want, avail
            )
            for sr in reads.values():
                for start, buf in self.backend.read_shard(
                    sr.shard, oid, sr.extents
                ).items():
                    smap.insert(sr.shard, start, buf)
            if need_decode:
                smap.decode(
                    self.codec, holes, self._object_sizes.get(oid, 0)
                )
        except Exception as e:
            self._read_errors[oid] = e
        self.cache.read_done(oid, smap)

    def _abort_op(self, op: ClientOp, err: Exception) -> None:
        """Fail an op cleanly AFTER it entered the cache: release the
        cache op (else its pinned lines wedge every later write to the
        object) and complete in order with the error."""
        op.error = err
        op.committed = True
        op.tracked.mark_event("aborted", err=type(err).__name__)
        self.perf.inc("aborts")
        if op.cache_op is not None and op.written is None:
            self.cache.write_done(op.cache_op, ShardExtentMap(self.sinfo))
        self._check_commit_order()

    def _cache_ready(self, op: ClientOp) -> None:
        """Old data present — encode and generate per-shard transactions
        (the cache_ready → generate_transactions hop, ECCommon.cc:688).
        Any failure in here (degraded read couldn't reconstruct, codec
        error) aborts the op in order instead of wedging the pipeline."""
        err = self._read_errors.pop(op.oid, None)
        if err is not None:
            self._abort_op(op, err)
            return
        op.tracked.mark_event("cache_ready")
        try:
            self._cache_ready_inner(op)
        except Exception as e:
            self._abort_op(op, e)

    def _cache_ready_inner(self, op: ClientOp) -> None:
        sinfo = self.sinfo
        old_map = op.cache_op.result
        old_size = self._object_sizes.get(op.oid, 0)
        new_size = max(old_size, op.ro_offset + len(op.data))

        new_map = ShardExtentMap(sinfo)
        pos = op.ro_offset
        data = np.frombuffer(op.data, dtype=np.uint8)
        taken = 0
        while taken < len(op.data):
            chunk_index = pos // sinfo.chunk_size
            raw = chunk_index % sinfo.k
            in_chunk = pos % sinfo.chunk_size
            take = min(sinfo.chunk_size - in_chunk, len(op.data) - taken)
            shard_off = (chunk_index // sinfo.k) * sinfo.chunk_size + in_chunk
            new_map.insert(
                sinfo.get_shard(raw), shard_off, data[taken : taken + take]
            )
            pos += take
            taken += take

        hinfo = self._get_hinfo(op.oid)
        hashed = hinfo.get_total_chunk_size()
        append_base = None
        if op.plan.do_parity_delta:
            new_map.encode_parity_delta(self.codec, old_map)
            hinfo.clear()  # overwrite invalidates cumulative shard crcs
        else:
            # merge old data under the new so parity encodes full stripes
            for shard in old_map.shards():
                if not sinfo.is_data_shard(shard):
                    continue
                for start, end in old_map.get_extent_set(shard):
                    gap = ExtentSet([(start, end)]).difference(
                        new_map.get_extent_set(shard)
                    )
                    for s, e in gap:
                        new_map.insert(shard, s, old_map.get(shard, s, e - s))
            lo, _hi = new_map.ro_range()
            if lo == hashed:
                append_base = hashed
            if append_base is not None:
                new_map.encode(
                    self.codec, hinfo, old_size=append_base,
                    csum_block=self.csum_block,
                )
            else:
                # not a contiguous append: cumulative crcs can't be
                # extended — invalidate (deep scrub then skips them)
                new_map.encode(self.codec, csum_block=self.csum_block)
                if hashed:
                    hinfo.clear()

        # size publishes BEFORE the dispatch: synchronous sub-write
        # acks can complete this op and cascade the NEXT queued op's
        # dispatch from inside _generate_transactions — assigning
        # afterwards would clobber whatever that nested op set (a
        # truncate queued behind a write lost its cut this way). On
        # dispatch failure the op aborts, so the size rolls back.
        prev = self._object_sizes.get(op.oid)
        self._object_sizes[op.oid] = new_size
        try:
            self._generate_transactions(op, new_map, new_size)
        except BaseException:
            if prev is None:
                self._object_sizes.pop(op.oid, None)
            else:
                self._object_sizes[op.oid] = prev
            raise
        self._eversions[op.oid] = (self.epoch, op.tid)
        self._live_eversions[op.oid] = (self.epoch, op.tid)

    def _get_hinfo(self, oid: str) -> HashInfo:
        if oid not in self._hinfo:
            self._hinfo[oid] = HashInfo(self.sinfo.k + self.sinfo.m)
        return self._hinfo[oid]

    def _generate_transactions(
        self, op: ClientOp, result: ShardExtentMap, new_size: int
    ) -> None:
        """Emit one Transaction per shard (ECTransaction.cc:916): the
        shard's written extents, a truncate to the new shard size, and
        the refreshed hinfo attr (ECTransaction.cc:497,902)."""
        sinfo = self.sinfo
        hinfo_bytes = self._get_hinfo(op.oid).to_bytes()
        # Dispatch to LIVE shards only: an acting-set hole (down OSD)
        # does not block the write — its extents are journaled in the
        # pg log for delta recovery when the shard returns (the
        # reference commits on the acting set, not k+m). Floor: k live
        # shards (min_size), else the object could become unreadable.
        live = set(self.backend.avail_shards())
        if len(live) < sinfo.k:
            # raises into _cache_ready's wrapper -> clean in-order abort
            raise IOError(
                f"only {len(live)} shards available, need {sinfo.k}"
            )
        op.pending_shards = set(live)
        written = ShardExtentMap(sinfo)
        op.written = written
        txns: list[tuple[int, Transaction]] = []
        for raw in range(sinfo.k + sinfo.m):
            shard = sinfo.get_shard(raw)
            txn = Transaction().touch(op.oid)
            shard_size = sinfo.object_size_to_shard_size(new_size, shard)
            for start, end in result.get_extent_set(shard):
                end = min(end, shard_size)
                if end <= start:
                    continue
                buf = bytes(result.get(shard, start, end - start))
                # fused-kernel csums ride the sub-write when they
                # describe this exact range (block-aligned within the
                # encode window) — the store adopts them instead of
                # re-hashing the bytes it just received
                blk = result.csums_for(shard, start, end - start)
                if blk is not None:
                    txn.write(
                        op.oid, start, buf, csums=blk,
                        csum_block=result.csums["block"],
                    )
                else:
                    txn.write(op.oid, start, buf)
                written.insert(shard, start, np.frombuffer(buf, np.uint8))
            self._stamp_identity(
                txn, op.oid, shard, new_size,
                (self.epoch, op.tid), hinfo_bytes, op.extra_attrs,
            )
            txns.append((shard, txn))
        if self.pglog is not None:
            # OI/HINFO ride every entry so the xattr-replay's merged
            # final state never regresses them to an older op's
            # values (a truncate's journaled size must not outlive a
            # later write's)
            self.pglog.append(
                op.tid,
                op.oid,
                {s: written.get_extent_set(s) for s in written.shards()},
                epoch=self.epoch,
                xattrs=self._journal_attrs(
                    new_size, (self.epoch, op.tid), hinfo_bytes,
                    op.extra_attrs,
                ),
            )
        op.tracked.mark_event(
            "encoded",
            strategy="delta" if op.plan.do_parity_delta else "full",
        )
        # crash point: plan chosen, stripe encoded, pg log appended —
        # nothing on the wire yet. A kill here loses the op entirely
        # (no shard saw it); the client's resend re-runs it whole.
        crash_points.fire(
            "rmw.prepare_done", daemon=self.owner, oid=op.oid,
            tid=op.tid,
        )
        op.tracked.mark_event("waiting_for_subops", n=len(live))
        # build every txn before the first dispatch: a synchronous ack
        # (local stores) must see the complete written map
        for shard, txn in txns:
            if shard not in live:
                continue  # hole: journaled above, recovered later
            self.backend.submit_shard_txn(
                shard, txn, lambda s=shard, o=op: self._shard_ack(o, s)
            )


    # -- shared identity plumbing (write + truncate txns) --------------
    @staticmethod
    def _stamp_identity(
        txn: Transaction, oid: str, shard: int, size: int,
        ev: "tuple[int, int]", hinfo_bytes: bytes,
        extra_attrs: "dict[str, bytes] | None",
    ) -> Transaction:
        """The per-shard identity-attr suffix every mutating txn
        carries — ONE implementation so the write and truncate paths
        cannot diverge (OI/HINFO/SI plus caller extras like the
        replicated reqid window)."""
        txn.setattr(oid, HINFO_KEY, hinfo_bytes)
        txn.setattr(oid, OI_KEY, pack_oi(size, ev))
        txn.setattr(oid, SI_KEY, str(shard).encode())
        for aname, aval in (extra_attrs or {}).items():
            txn.setattr(oid, aname, aval)
        return txn

    @staticmethod
    def _journal_attrs(
        size: int, ev: "tuple[int, int]", hinfo_bytes: bytes,
        extra_attrs: "dict[str, bytes] | None",
    ) -> "dict[str, bytes]":
        """The xattrs journaled with each entry so a shard that missed
        the op replays the SAME identity state the txns carried —
        including the reqid window (a recovered shard that later hosts
        the primary must not lose failover dedup)."""
        return {
            OI_KEY: pack_oi(size, ev),
            HINFO_KEY: hinfo_bytes,
            **(extra_attrs or {}),
        }

    def _shard_ack(self, op: ClientOp, shard: int) -> None:
        finish = False
        with self._ack_lock:
            if len(op.pending_shards) == 1 and shard in op.pending_shards:
                # final sub-write reply for this op: the reference
                # consults ECInject write type 2 here (pending_commits
                # == 1 in handle_sub_write_reply, ECBackend.cc:1158-
                # 1167) and, if armed, has the primary mark ITSELF
                # down via mon command. Hook check FIRST: where no
                # down-hook exists the armed rule must not be consumed
                # to no effect.
                from .inject import ec_inject

                if self.on_osd_down_inject is not None and (
                    ec_inject.test_write_error2(op.oid)
                ):
                    self.on_osd_down_inject()
            if self.pglog is not None:
                self.pglog.ack(shard, op.tid)
            op.pending_shards.discard(shard)
            op.acked_shards.add(shard)
            op.tracked.mark_event("subop_ack", shard=shard)
            if not op.pending_shards and not op.committed:
                # crash point: every sub-write durable on its shard,
                # the commit decision not yet taken. A kill here is
                # the fully-applied-but-unreported crash: replay must
                # ROLL FORWARD (all shards agree) and the client's
                # resend dedup via the replicated reqid window.
                crash_points.fire(
                    "rmw.primary_before_commit", daemon=self.owner,
                    oid=op.oid, tid=op.tid,
                )
                op.committed = True
                op.tracked.mark_event("committed")
                finish = True
        # cache release OUTSIDE the ack lock: write_done may dispatch
        # the next queued op for this object, whose RMW backend read
        # blocks on the messenger — IO must never run under _ack_lock
        # (ABBA with the reply-pump thread's _shard_ack)
        if finish:
            self.cache.write_done(op.cache_op, op.written)
            with self._ack_lock:
                self._check_commit_order()

    def on_shard_down(self, shard: int) -> None:
        """An acting member died with sub-write acks outstanding: those
        acks will never arrive. Commit parked ops on the surviving set
        — the mirror of the hole-journaling ``_dispatch_writes``
        applies when the member is already down at dispatch time. The
        pg log is NOT acked for the dead shard, so its missed extents
        stay dirty for delta recovery when it returns (the reference
        requeues the op into the new interval; the client's resend
        dedups via reqid).

        Durability floor: an op may only report success if at least k
        shards actually acked — the same min_size floor
        ``_generate_transactions`` enforces at dispatch. Below that the
        new stripe cannot be decoded (survivors mix old and new
        chunks), so the op completes with an error instead."""
        finished: list[ClientOp] = []
        with self._ack_lock:
            for op in list(self._inflight.values()):
                if shard in op.pending_shards:
                    op.pending_shards.discard(shard)
                    op.tracked.mark_event("subop_lost", shard=shard)
                    if not op.pending_shards and not op.committed:
                        if len(op.acked_shards) < self.sinfo.k:
                            op.error = IOError(
                                f"write lost below min_size: only "
                                f"{len(op.acked_shards)} of {self.sinfo.k}"
                                f" required shards durable"
                            )
                            self.perf.inc("aborts")
                        op.committed = True
                        finished.append(op)
        # cache release outside _ack_lock (see _shard_ack). A failed
        # op publishes an EMPTY map, exactly like _abort_op: the cache
        # must not serve bytes the client was told were lost.
        for op in finished:
            self.cache.write_done(
                op.cache_op,
                op.written if op.error is None
                else ShardExtentMap(self.sinfo),
            )
        with self._ack_lock:
            self._check_commit_order()

    def on_shard_recovered(
        self, shard: int, up_to_tid: int | None = None
    ) -> None:
        """Log-driven recovery rebuilt this shard's missed extents:
        treat the lost sub-write acks as durable and let parked ops
        commit — the rollforward of partially-committed EC writes
        (pending_roll_forward semantics, ECCommon.h:500-503 + PGLog)."""
        with self._ack_lock:
            self._on_shard_recovered_locked(shard, up_to_tid)

    def _on_shard_recovered_locked(
        self, shard: int, up_to_tid: int | None
    ) -> None:
        for tid, op in list(self._inflight.items()):
            if up_to_tid is not None and tid > up_to_tid:
                continue
            if shard in op.pending_shards:
                self._shard_ack(op, shard)

    def _check_commit_order(self) -> None:
        """Fire on_commit strictly in tid order (waiting_commit /
        completed_to semantics, ECCommon.h:553-555)."""
        while self._inflight:
            tid, op = next(iter(self._inflight.items()))
            if not op.committed:
                return
            self._inflight.pop(tid)
            op.notified = True
            op.tracked.finish(
                "done" if op.error is None
                else f"error:{type(op.error).__name__}"
            )
            if op.t_submit is not None:
                self.perf.ainc(
                    "commit_lat", time.perf_counter() - op.t_submit
                )
            if op.on_commit is not None:
                op.on_commit(op)
