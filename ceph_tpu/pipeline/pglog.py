"""Write-ahead op log — the ``PGLog`` analog (osd/PGLog.{h,cc}).

The reference's per-PG log is the replayable journal that makes
recovery DELTA-shaped: a shard that missed some sub-writes (dropped
ack, brief outage) catches up by re-fetching only the extents written
since its last completed version, instead of a full backfill
(SURVEY.md §5.4; divergent-entry rollback/rollforward is the
``completed_to``/``pending_roll_forward`` machinery of ECCommon.h:500).

Here: the RMW pipeline appends one entry per client write (tid-ordered
— tids ARE the version numbers, the eversion analog) recording the
per-shard extents the write touched, and records per-shard acks.
``completed_to(shard)`` is the max contiguous acked tid;
``dirty_extents(shard)`` is the union of extents written past it —
exactly what delta recovery must rebuild. ``trim`` drops entries every
shard has completed (log bounded like the reference's
osd_min_pg_log_entries window).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .extents import ExtentSet


@dataclass
class LogEntry:
    """One client op (the pg_log_entry_t analog). ``delete`` entries
    (pg_log_entry_t::DELETE) touch every shard and supersede earlier
    writes of the oid for recovery purposes. ``xattrs`` records user-
    attr mutations (value None = removed) — they replicate to every
    shard, so replay needs them like data extents."""

    tid: int
    oid: str
    shard_extents: dict[int, ExtentSet] = field(default_factory=dict)
    delete: bool = False
    xattrs: "dict[str, bytes | None] | None" = None
    #: map epoch at append time; (epoch, tid) is the entry's eversion
    epoch: int = 0


class PGLog:
    def __init__(self, n_shards: int) -> None:
        self.n_shards = n_shards
        self.entries: list[LogEntry] = []  # tid-ascending
        self._acked: dict[int, set[int]] = {s: set() for s in range(n_shards)}
        self._completed: dict[int, int] = {s: 0 for s in range(n_shards)}
        self.tail = 0  # tids <= tail are trimmed

    # -- write path hooks ----------------------------------------------
    def append(
        self, tid: int, oid: str, shard_extents: dict[int, ExtentSet],
        epoch: int = 0,
        xattrs: "dict[str, bytes | None] | None" = None,
    ) -> None:
        """``xattrs`` may carry identity attrs (OI/HINFO) alongside
        the extents: a shard that misses a size-changing op (truncate,
        grow) with no replayable extents still needs the new OI — a
        stale size on a later primary takeover would clip the
        object."""
        if self.entries and tid <= self.entries[-1].tid:
            raise ValueError(f"non-monotonic log append: tid {tid}")
        self.entries.append(
            LogEntry(
                tid, oid,
                {s: es.copy() for s, es in shard_extents.items()},
                xattrs=dict(xattrs) if xattrs else None,
                epoch=epoch,
            )
        )

    def last_eversion(self, oid: str) -> "tuple[int, int] | None":
        """(epoch, tid) of the newest in-window entry touching the
        oid — the authoritative eversion as far as the log knows."""
        for e in reversed(self.entries):
            if e.oid == oid:
                return None if e.delete else (e.epoch, e.tid)
        return None

    def append_delete(self, tid: int, oid: str) -> None:
        """Record a whole-object remove: a shard that misses it would
        otherwise RESURRECT the object during delta recovery."""
        if self.entries and tid <= self.entries[-1].tid:
            raise ValueError(f"non-monotonic log append: tid {tid}")
        self.entries.append(LogEntry(tid, oid, {}, delete=True))

    def append_xattrs(
        self, tid: int, oid: str, xattrs: "dict[str, bytes | None]"
    ) -> None:
        """Record replicated-attr mutations by FULL attr key
        (u:/m:-prefixed; None = removal)."""
        if self.entries and tid <= self.entries[-1].tid:
            raise ValueError(f"non-monotonic log append: tid {tid}")
        self.entries.append(LogEntry(tid, oid, {}, xattrs=dict(xattrs)))

    def ack(self, shard: int, tid: int) -> None:
        """A shard durably applied its sub-write for ``tid``."""
        if tid <= self._completed[shard]:
            return  # already covered (e.g. a post-recovery rollforward)
        acked = self._acked[shard]
        acked.add(tid)
        # advance the contiguous frontier
        c = self._completed[shard]
        while (c + 1) in acked or self._is_gap(c + 1):
            if (c + 1) in acked:
                acked.discard(c + 1)
            c += 1
        self._completed[shard] = c

    def _is_gap(self, tid: int) -> bool:
        """Tids the log never saw (aborted writes) don't block the
        frontier."""
        if tid > (self.entries[-1].tid if self.entries else self.tail):
            return False
        if tid <= self.tail:
            return True
        return all(e.tid != tid for e in self.entries)

    # -- recovery surface ----------------------------------------------
    def completed_to(self, shard: int) -> int:
        return self._completed[shard]

    def head(self) -> int:
        return self.entries[-1].tid if self.entries else self.tail

    def dirty_extents(self, shard: int) -> dict[str, ExtentSet]:
        """Per-object extents this shard is missing: everything written
        past its contiguous frontier (the missing-set computation of
        PGLog::merge_log, as extents instead of whole objects). A
        delete entry resets the oid — only writes AFTER the last
        delete count (the object was recreated)."""
        frontier = self._completed[shard]
        out: dict[str, ExtentSet] = {}
        for e in self.entries:
            if e.tid <= frontier:
                continue
            if e.delete:
                out.pop(e.oid, None)
                continue
            es = e.shard_extents.get(shard)
            if not es:
                continue
            acc = out.setdefault(e.oid, ExtentSet())
            for start, end in es:
                acc.insert(start, end - start)
        return out

    def dirty_deletes(self, shard: int) -> set[str]:
        """Oids whose FINAL state past the shard's frontier is
        'removed' — recovery must apply the delete, not rebuild data."""
        frontier = self._completed[shard]
        out: set[str] = set()
        for e in self.entries:
            if e.tid <= frontier:
                continue
            if e.delete:
                out.add(e.oid)
            elif e.shard_extents.get(shard):
                out.discard(e.oid)  # recreated after the delete
        return out

    def dirty_xattrs(
        self, shard: int
    ) -> "dict[str, dict[str, bytes | None]]":
        """Per-object FINAL user-attr state this shard is missing
        (entries past its frontier; a delete resets the object)."""
        frontier = self._completed[shard]
        out: dict[str, dict[str, bytes | None]] = {}
        for e in self.entries:
            if e.tid <= frontier:
                continue
            if e.delete:
                out.pop(e.oid, None)
                continue
            if e.xattrs:
                out.setdefault(e.oid, {}).update(e.xattrs)
        return out

    def mark_recovered(self, shard: int, up_to: int | None = None) -> None:
        """Delta recovery finished: the shard now reflects every write
        through ``up_to`` (default: the log head)."""
        target = self.head() if up_to is None else up_to
        self._completed[shard] = max(self._completed[shard], target)
        self._acked[shard] = {
            t for t in self._acked[shard] if t > target
        }

    def trim(self) -> int:
        """Drop entries all shards have completed; returns new tail
        (PGLog::trim)."""
        floor = min(self._completed.values())
        kept = [e for e in self.entries if e.tid > floor]
        trimmed = len(self.entries) - len(kept)
        self.entries = kept
        self.tail = max(self.tail, floor)
        return trimmed

    def __len__(self) -> int:
        return len(self.entries)
