"""Shard recovery + deep scrub — the ``ECBackend::RecoveryBackend`` and
``be_deep_scrub`` analogs.

Recovery mirrors the reference's backfill of a failed shard
(osd/ECBackend.h:191-198 RecoveryOp FSM IDLE→READING→WRITING→COMPLETE,
ECBackend.cc:298-530 ``continue_recovery_op``): plan the minimum read
set over the survivors (CLAY's fractional-repair sub-chunk plan rides
the same seam — reads only ``(d·chunk)/(d-k+1)`` bytes), reconstruct
the lost shard in one batched device dispatch, then push it to the
replacement store together with the restored ``hinfo`` attr (the Push
message analog).

Deep scrub mirrors ECBackend::be_deep_scrub (osd/ECBackend.cc:1769,
CRC check :1829-1869): every shard's stored bytes are CRC32C'd from the
seed and compared against the object's persisted ``HashInfo``; a
mismatched shard is reported so recovery can rebuild it. The CRC rides
``checksum.crc32c_stream`` — device-batched fold above the
``csum_device_min_bytes`` threshold, host scalar below — so scrubbing
a large object no longer serializes through the host hash. Recovery
verifies fully reconstructed shards against the persisted HashInfo the
same way (``ec_recovery_verify``) BEFORE pushing them: a miscomputed
or bit-flipped rebuild can never silently replace a shard.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.checksum import crc32c_stream
from ceph_tpu.store import Transaction

from .extents import ExtentSet
from .hashinfo import SEED, HashInfo
from .read import (
    ShardRead,
    get_min_avail_to_read_shards,
    reconstruct_shards,
)
from .rmw import HINFO_KEY, OI_KEY, SI_KEY, pack_oi
from .shard_map import ShardExtentMap
from .stripe import StripeInfo


class RecoveryState(enum.Enum):
    """ECBackend.h:191-198."""

    IDLE = "IDLE"
    READING = "READING"
    WRITING = "WRITING"
    COMPLETE = "COMPLETE"


class RecoveryOp:
    """One object's recovery (the RecoveryBackend::RecoveryOp analog)."""

    def __init__(self, oid: str, missing: set[int]) -> None:
        self.oid = oid
        self.missing = set(missing)
        self.state = RecoveryState.IDLE
        self.want: dict[int, ExtentSet] = {}
        self.shard_reads: dict[int, ShardRead] = {}
        self.result: ShardExtentMap | None = None
        self.error_shards: set[int] = set()
        self.pending_reads: set[int] = set()
        self.pending_pushes: set[int] = set()
        self.recovered_bytes = 0
        self.read_bytes = 0
        self.error: Exception | None = None
        # Optional per-shard extent restriction (delta recovery).
        self.extent_override: dict[int, ExtentSet] | None = None
        # Optional object-size override (peer-reported size).
        self.size_override: int | None = None


class RecoveryBackend:
    """Rebuild missing shards of an object onto their (replacement)
    stores; drive with ``recover_object`` or step the FSM manually via
    ``continue_recovery_op``."""

    def __init__(
        self,
        sinfo: StripeInfo,
        codec,
        backend,
        size_fn,
        hinfo_fn,
        perf_name: str = "ec_recovery",
        user_attrs_fn=None,
        eversion_fn=None,
    ) -> None:
        self.sinfo = sinfo
        self.codec = codec
        self.backend = backend
        self.size_fn = size_fn
        self.hinfo_fn = hinfo_fn
        #: oid -> authoritative (epoch, tid) to stamp into pushed OI
        #: attrs (None = stamp the null eversion)
        self.eversion_fn = eversion_fn
        #: oid -> {attr name: bytes} of USER attrs to restore with a
        #: push (the primary's copy — user xattrs replicate everywhere)
        self.user_attrs_fn = user_attrs_fn
        from ceph_tpu.utils import PerfCountersBuilder, perf_collection

        self.perf = (
            PerfCountersBuilder(perf_collection, perf_name)
            .add_u64_counter("recovery_ops", "objects recovered")
            .add_u64_counter("recovery_read_bytes",
                             "survivor bytes read for recovery")
            .add_u64_counter("recovered_bytes", "bytes pushed to targets")
            .add_u64_counter("errors", "recoveries failed")
            .create_perf_counters()
        )

    # -- FSM -------------------------------------------------------------
    def open_recovery_op(self, oid: str, missing: set[int]) -> RecoveryOp:
        return RecoveryOp(oid, missing)

    def continue_recovery_op(self, op: RecoveryOp) -> RecoveryState:
        """Advance one state (continue_recovery_op, ECBackend.cc:298)."""
        if op.state is RecoveryState.IDLE:
            self._start_reads(op)
        elif op.state is RecoveryState.READING:
            if not op.pending_reads and op.error is None:
                self._start_writes(op)
            elif op.error is not None:
                op.state = RecoveryState.COMPLETE
        elif op.state is RecoveryState.WRITING:
            if not op.pending_pushes:
                op.state = RecoveryState.COMPLETE
        return op.state

    def recover_object(
        self,
        oid: str,
        missing: set[int],
        extents: "dict[int, ExtentSet] | None" = None,
        size: int | None = None,
    ) -> RecoveryOp:
        """Run the FSM to completion. Backends with a ``drain_until``
        event loop (the networked one) are drained between states.
        ``extents`` restricts the rebuild per shard — the log-driven
        delta-recovery path (see ``recover_from_log``). ``size``
        overrides size_fn when the caller knows the object size from a
        source the local state doesn't reflect (a peer's report)."""
        from ceph_tpu.utils import tracer
        from ceph_tpu.utils.optracker import op_tracker

        drain = getattr(self.backend, "drain_until", None)
        op = self.open_recovery_op(oid, missing)
        op.extent_override = extents
        op.size_override = size
        tracked = op_tracker.register(
            "recovery_push", daemon=self.perf.name, oid=oid,
            missing=sorted(missing),
        )
        try:
            with tracer.span(
                "ec_recover", oid=oid, missing=sorted(missing)
            ):
                while op.state is not RecoveryState.COMPLETE:
                    before = op.state
                    self.continue_recovery_op(op)
                    if op.state is not before:
                        tracked.mark_event(op.state.value.lower())
                    if op.state is before and op.error is not None:
                        break
                    if op.state is before:
                        if drain is not None and op.pending_reads:
                            drain(
                                lambda: not op.pending_reads or op.error
                            )
                        elif drain is not None and op.pending_pushes:
                            drain(lambda: not op.pending_pushes)
                        else:
                            raise RuntimeError(
                                f"recovery stalled in {op.state} "
                                f"for {oid!r}"
                            )
        except BaseException as e:
            tracked.finish(f"error:{type(e).__name__}")
            raise
        if op.error is not None:
            tracked.finish(f"error:{type(op.error).__name__}")
            self.perf.inc("errors")
            raise op.error
        tracked.finish("done")
        self.perf.inc("recovery_ops")
        self.perf.inc("recovery_read_bytes", op.read_bytes)
        self.perf.inc("recovered_bytes", op.recovered_bytes)
        return op

    def _op_size(self, op: RecoveryOp) -> int:
        return (
            op.size_override if op.size_override is not None
            else self.size_fn(op.oid)
        )

    def _start_reads(self, op: RecoveryOp) -> None:
        size = self._op_size(op)
        op.want = {}
        for shard in op.missing:
            ssize = self.sinfo.object_size_to_exact_shard_size(size, shard)
            if ssize <= 0:
                continue
            if op.extent_override is not None:
                es = op.extent_override.get(shard, ExtentSet())
                clipped = ExtentSet()
                for start, end in es:
                    if start < ssize:
                        clipped.insert(start, min(end, ssize) - start)
                if clipped:
                    op.want[shard] = clipped
            else:
                op.want[shard] = ExtentSet([(0, ssize)])
        op.result = ShardExtentMap(self.sinfo)
        op.state = RecoveryState.READING
        if not op.want:
            return  # no bytes to read; WRITING still restores the
            # object's existence + attrs on the missing shards
        avail = self.backend.avail_shards() - op.missing
        try:
            op.shard_reads, _ = get_min_avail_to_read_shards(
                self.sinfo, self.codec, op.want, avail
            )
        except ValueError as e:
            op.error = e
            return
        op.pending_reads = set(op.shard_reads)
        for sr in list(op.shard_reads.values()):
            self.backend.read_shard_async(
                sr.shard,
                op.oid,
                sr.extents,
                lambda shard, result, _op=op: self._read_done(
                    _op, shard, result
                ),
            )

    def _read_done(self, op: RecoveryOp, shard: int, result) -> None:
        op.pending_reads.discard(shard)
        if isinstance(result, Exception):
            # Recovery retry policy mirrors reads: drop the shard and
            # re-plan; a second loss during recovery is still decodable
            # while survivors >= k.
            op.error_shards.add(shard)
            avail = (
                self.backend.avail_shards() - op.missing - op.error_shards
            )
            try:
                reads, _ = get_min_avail_to_read_shards(
                    self.sinfo, self.codec, op.want, avail
                )
            except ValueError as e:
                op.error = e
                return
            for s, sr in op.shard_reads.items():
                new = reads.get(s)
                sr.subchunks = new.subchunks if new is not None else None
            fresh = {
                s: sr
                for s, sr in reads.items()
                if s not in op.shard_reads and s not in op.error_shards
            }
            op.shard_reads.update(fresh)
            op.pending_reads.update(fresh)
            for sr in list(fresh.values()):
                self.backend.read_shard_async(
                    sr.shard,
                    op.oid,
                    sr.extents,
                    lambda s2, r2, _op=op: self._read_done(_op, s2, r2),
                )
        else:
            for start, buf in result.items():
                op.result.insert(shard, start, buf)
                op.read_bytes += len(buf)

    def _start_writes(self, op: RecoveryOp) -> None:
        size = self._op_size(op)
        try:
            reconstruct_shards(
                self.sinfo,
                self.codec,
                op.result,
                op.want,
                op.shard_reads,
                size,
                op.error_shards,
            )
        except ValueError as e:
            op.error = e
            op.state = RecoveryState.COMPLETE
            return
        op.state = RecoveryState.WRITING
        hinfo = self.hinfo_fn(op.oid)
        err = self._verify_reconstructed(op, hinfo)
        if err is not None:
            op.error = err
            op.state = RecoveryState.COMPLETE
            return
        hinfo_bytes = hinfo.to_bytes() if hinfo is not None else None
        # Every missing shard gets a push: zero-length tail shards
        # still carry the object (touch) and its hinfo attr, exactly
        # as the original write's per-shard transaction did.
        op.pending_pushes = set(op.missing)
        user_attrs = (
            self.user_attrs_fn(op.oid)
            if self.user_attrs_fn is not None else {}
        )
        for shard in sorted(op.missing):
            txn = Transaction().touch(op.oid)
            # Truncate to the authoritative shard length: a DIVERGENT
            # target (eversion rollback) may hold a LONGER stale copy
            # whose garbage tail would otherwise survive the rebuild
            # (absent-shard pushes truncate to a no-op).
            txn.truncate(
                op.oid,
                max(
                    self.sinfo.object_size_to_exact_shard_size(size, shard),
                    0,
                ),
            )
            for start, end in op.want.get(shard, ExtentSet()):
                buf = bytes(op.result.get(shard, start, end - start))
                txn.write(op.oid, start, buf)
                op.recovered_bytes += len(buf)
            if hinfo_bytes is not None:
                txn.setattr(op.oid, HINFO_KEY, hinfo_bytes)
            # identity attrs, as the original write txn carried them:
            # size for new-primary takeover, shard index for the
            # misplacement guard
            ev = (
                self.eversion_fn(op.oid) if self.eversion_fn else None
            ) or (0, 0)
            txn.setattr(op.oid, OI_KEY, pack_oi(size, ev))
            txn.setattr(op.oid, SI_KEY, str(shard).encode())
            for aname, aval in user_attrs.items():
                txn.setattr(op.oid, aname, aval)
            self.backend.submit_shard_txn(
                shard,
                txn,
                lambda s=shard, o=op: o.pending_pushes.discard(s),
            )
        if not op.pending_pushes:
            op.state = RecoveryState.COMPLETE

    def _verify_reconstructed(
        self, op: RecoveryOp, hinfo
    ) -> "Exception | None":
        """Check a FULL rebuild against the persisted cumulative shard
        crcs before anything is pushed (be_deep_scrub applied to the
        decode output, device-batched via crc32c_stream). Skipped for
        delta recovery (partial extents can't reproduce a cumulative
        hash) and for objects whose hashes were invalidated by an
        overwrite — exactly the windows deep scrub skips too."""
        from ceph_tpu.utils import config

        if (
            not config.get("ec_recovery_verify")
            or hinfo is None
            or op.extent_override is not None
        ):
            return None
        hashed = hinfo.get_total_chunk_size()
        if hashed == 0:
            return None
        for shard in sorted(op.missing):
            if shard not in op.want:
                continue  # zero-length tail shard: nothing rebuilt
            # absent bytes read as zeros — the encode-time zero-pad
            # convention the cumulative hashes were built under
            got = crc32c_stream(
                op.result.get(shard, 0, hashed), SEED
            )
            want = hinfo.get_chunk_hash(shard)
            if got != want:
                return IOError(
                    f"reconstructed shard {shard} of {op.oid!r} fails "
                    f"HashInfo verify: got {got:#x} want {want:#x}"
                )
        return None

    # -- log-driven delta recovery (PGLog missing-set replay) ----------
    def recover_from_log(self, pglog, shard: int) -> dict[str, RecoveryOp]:
        """Catch a lagging shard up from the op log: rebuild ONLY the
        extents written past its contiguous frontier — the delta
        recovery PGLog exists for, vs. full backfill (osd/PGLog.h
        missing-set semantics). Marks the shard recovered on success."""
        head = pglog.head()
        ops: dict[str, RecoveryOp] = {}
        # deletes first: a shard that missed a remove still holds the
        # object's stale bytes — resurrection unless replayed
        drain = getattr(self.backend, "drain_until", None)
        pending: set[str] = set()
        for oid in sorted(pglog.dirty_deletes(shard)):
            pending.add(oid)
            self.backend.submit_shard_txn(
                shard,
                Transaction().touch(oid).remove(oid),
                lambda o=oid: pending.discard(o),
            )
        if pending and drain is not None:
            drain(lambda: not pending)
        for oid, extents in sorted(pglog.dirty_extents(shard).items()):
            ops[oid] = self.recover_object(
                oid, {shard}, extents={shard: extents}
            )
        # user-xattr replay: push the FINAL attr state the shard missed
        # (tombstones as tolerant rmattrs — it may never have had them)
        xdirty = pglog.dirty_xattrs(shard)
        xpending: set[str] = set()
        for oid, attrs in sorted(xdirty.items()):
            txn = Transaction().touch(oid)
            for name, val in sorted(attrs.items()):  # FULL attr keys
                if val is None:
                    txn.rmattr(oid, name, ignore_missing=True)
                else:
                    txn.setattr(oid, name, val)
            xpending.add(oid)
            self.backend.submit_shard_txn(
                shard, txn, lambda o=oid: xpending.discard(o)
            )
        if xpending and drain is not None:
            drain(lambda: not xpending)
        pglog.mark_recovered(shard, head)
        return ops


# -- deep scrub ---------------------------------------------------------


@dataclass
class ScrubError:
    shard: int
    kind: str  # "missing_attr" | "crc_mismatch" | "read_error"
    detail: str = ""


@dataclass
class ScrubResult:
    oid: str
    errors: list[ScrubError] = field(default_factory=list)
    repaired: bool = False

    @property
    def ok(self) -> bool:
        return not self.errors


def be_deep_scrub(
    sinfo: StripeInfo,
    backend,
    oid: str,
    hinfo: HashInfo | None = None,
) -> ScrubResult:
    """Verify every shard's stored bytes against the persisted HashInfo
    CRCs (ECBackend.cc:1829-1869).

    ``hinfo`` defaults to the attr stored on shard 0 (all shards carry
    the same copy — written transactionally with the data). Shards
    whose hashes were invalidated by an overwrite (cleared hinfo) scrub
    as OK with zero coverage, mirroring the reference's skip.
    """
    result = ScrubResult(oid)
    if hinfo is None:
        for shard in sorted(backend.avail_shards()):
            try:
                raw = backend.stores[shard].getattr(oid, HINFO_KEY)
                hinfo = HashInfo.from_bytes(raw)
                break
            except (FileNotFoundError, KeyError):
                continue
        if hinfo is None:
            result.errors.append(ScrubError(-1, "missing_attr"))
            return result
    hashed = hinfo.get_total_chunk_size()
    if hashed == 0:
        return result  # cleared / empty: nothing to verify
    from ceph_tpu.utils import config

    stride = max(int(config.get("osd_deep_scrub_stride")), 4096)
    for shard in sorted(backend.avail_shards()):
        store = backend.stores[shard]
        # Stride-bounded reads (osd_deep_scrub_stride): the CRC chains
        # across pieces, so scrub memory/latency stays bounded no
        # matter the object size (ECBackend.cc:1793-1795).
        crc = SEED
        missing = False
        for off in range(0, hashed, stride):
            want_len = min(stride, hashed - off)
            try:
                buf = store.read(oid, off, want_len)
            except FileNotFoundError:
                result.errors.append(
                    ScrubError(shard, "read_error", "missing")
                )
                missing = True
                break
            # Ragged tails: stored bytes short of the hashed window
            # were hashed as zeros at encode time (zero-padding).
            if len(buf) < want_len:
                buf = buf + b"\0" * (want_len - len(buf))
            crc = crc32c_stream(buf, crc)
        if missing:
            continue
        want = hinfo.get_chunk_hash(shard)
        if crc != want:
            result.errors.append(
                ScrubError(
                    shard, "crc_mismatch", f"got {crc:#x} want {want:#x}"
                )
            )
    return result
