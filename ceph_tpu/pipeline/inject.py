"""Fault injection — the ``ECInject`` analog (osd/ECInject.{h,cc}).

A process-global registry of per-object (optionally per-shard) error
injections, consulted from the sub-read / sub-write dispatch paths
exactly where the reference hooks ``handle_sub_read`` /
``handle_sub_write``:

- read type 0: sub-read fails with EIO.
- read type 1: shard reports the object missing (ENOENT-alike) —
  exercises the same retry path with a different error class.
- read type 2: SILENT corruption — the sub-read succeeds but the
  returned shard payload has bytes flipped. Nothing errors at the
  transport: only an integrity tier (BlockStore csums at rest, deep
  scrub's HashInfo comparison, the client's content verify) can
  catch it — the bit-rot-on-the-wire / buggy-drive-firmware case.
- write type 0: the client write op fails before dispatch (abort).
- write type 1: the sub-write to a shard is silently dropped — the ack
  never arrives, leaving the op parked in the in-order commit queue
  (the rollback-forcing inject of the reference). Firing auto-arms a
  type-2 inject on the same object, exactly as the reference does
  (ECInject.cc test_write_error1 → write_error(o, 2, 0, 1)).
- write type 2: "inject OSD down" — consulted on the primary when the
  final sub-write commit arrives (pending_commits == 1 in
  handle_sub_write_reply, ECBackend.cc:1158-1167); the primary marks
  itself down via the mon-command analog.
- write type 3: "write abort OSDs" — consulted in handle_sub_write
  (ECBackend.cc:922-926); the receiving OSD aborts (``ceph_abort``),
  so the write is never applied and the ack never arrives. The
  reference requires duration == 1 for this type.

Each injection has ``when`` (ops to let through first) and ``duration``
(ops to affect) counters, matching the reference's tell-command
parameters (ECInject.cc:47-69). Thread-safe; tests and the chaos
harness drive it.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from ceph_tpu.utils.lockdep import DebugLock

ANY_SHARD = -1


def _base_oid(oid: str) -> str:
    """Strip a per-shard store-key suffix (``<oid>#s<n>``, the
    ghobject shard_id field) — object-wide rules (write types 2/3) are
    keyed by the base object, the way the reference normalizes
    ghobject→NO_SHARD before touching write_failures2/3
    (ECInject.cc test_write_error2/3)."""
    loc, sep, s = oid.rpartition("#s")
    if sep and s.isdigit():
        return loc
    return oid


@dataclass
class _Rule:
    when: int
    duration: int

    def fires(self) -> bool:
        """Count an op against this rule; True if the error injects."""
        if self.when > 0:
            self.when -= 1
            return False
        if self.duration > 0:
            self.duration -= 1
            return True
        return False

    @property
    def exhausted(self) -> bool:
        return self.when <= 0 and self.duration <= 0


class ECInject:
    """Global error-inject registry (singleton via module instance)."""

    def __init__(self) -> None:
        self._lock = DebugLock("ec.inject")
        # (kind, type, oid, shard) -> _Rule
        self._rules: dict[tuple[str, int, str, int], _Rule] = {}
        self.injected_count = 0

    # -- operator surface (the `ceph tell` analog) ---------------------
    def read_error(
        self, oid: str, type: int, when: int = 0, duration: int = 1,
        shard: int = ANY_SHARD,
    ) -> str:
        if type not in (0, 1, 2):
            return "unrecognized error inject type"
        with self._lock:
            self._rules[("read", type, oid, shard)] = _Rule(when, duration)
        return f"ok: read error type {type} on {oid}"

    def write_error(
        self, oid: str, type: int, when: int = 0, duration: int = 1,
        shard: int = ANY_SHARD,
    ) -> str:
        if type not in (0, 1, 2, 3):
            return "unrecognized error inject type"
        if type == 3 and duration != 1:
            # the reference refuses multi-shot OSD aborts
            # (ECInject.cc write_error case 3)
            return "duration must be 1"
        if type in (2, 3):
            shard = ANY_SHARD  # registered object-wide, never per-shard
            oid = _base_oid(oid)
        with self._lock:
            self._rules[("write", type, oid, shard)] = _Rule(when, duration)
        return f"ok: write error type {type} on {oid}"

    def clear_read_error(self, oid: str, type: int, shard: int = ANY_SHARD) -> str:
        with self._lock:
            self._rules.pop(("read", type, oid, shard), None)
        return "ok"

    def clear_write_error(self, oid: str, type: int, shard: int = ANY_SHARD) -> str:
        with self._lock:
            self._rules.pop(("write", type, oid, shard), None)
        return "ok"

    def clear_all(self) -> None:
        with self._lock:
            self._rules.clear()
            self.injected_count = 0

    # -- test hooks (called from the dispatch paths) -------------------
    def _test(self, kind: str, type: int, oid: str, shard: int) -> bool:
        with self._lock:
            for key in (
                (kind, type, oid, shard),
                (kind, type, oid, ANY_SHARD),
            ):
                rule = self._rules.get(key)
                if rule is None:
                    continue
                fired = rule.fires()
                if rule.exhausted:
                    del self._rules[key]
                if fired:
                    self.injected_count += 1
                    return True
        return False

    def test_read_error0(self, oid: str, shard: int) -> bool:
        return self._test("read", 0, oid, shard)

    def test_read_error1(self, oid: str, shard: int) -> bool:
        return self._test("read", 1, oid, shard)

    def test_read_error2(self, oid: str, shard: int) -> bool:
        """Silent corruption: the consult site flips bytes in the
        payload it is about to return (no error surfaces here)."""
        return self._test("read", 2, oid, shard)

    @staticmethod
    def corrupt(buf: bytes) -> bytes:
        """The canonical payload mangling for read type 2: invert the
        first byte (and one mid-buffer byte for runs long enough to
        span csum blocks) — enough for any integrity check, invisible
        to everything else."""
        if not buf:
            return buf
        out = bytearray(buf)
        out[0] ^= 0xFF
        if len(out) > 4096:
            out[4096] ^= 0xFF
        return bytes(out)

    def test_write_error0(self, oid: str) -> bool:
        return self._test("write", 0, oid, ANY_SHARD)

    def test_write_error1(self, oid: str, shard: int) -> bool:
        fired = self._test("write", 1, oid, shard)
        if fired:
            # a dropped sub-write arms an OSD-down inject on the same
            # object (ECInject.cc test_write_error1): the next commit
            # cycle takes the primary down, forcing the rollback path.
            # Keyed by the BASE object — the consult site passes the
            # client oid, not the per-shard store key.
            self.write_error(_base_oid(oid), 2, 0, 1)
        return fired

    def test_write_error2(self, oid: str) -> bool:
        return self._test("write", 2, _base_oid(oid), ANY_SHARD)

    def test_write_error3(self, oid: str, exact: bool = False) -> bool:
        """``exact=True`` consults the rule under the oid as given (no
        ghobject normalization) — the standalone pipeline tier uses it
        so a rule the daemon tier already consulted (with the
        normalized base oid) is not decremented a second time by the
        nested ShardBackend hop."""
        return self._test(
            "write", 3, oid if exact else _base_oid(oid), ANY_SHARD
        )


# The process-global registry, mirroring the reference's namespace-level
# singleton state.
ec_inject = ECInject()
