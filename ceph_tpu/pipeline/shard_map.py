"""Per-shard extent maps of buffers — the ``shard_extent_map_t`` analog.

Mirrors osd/ECUtil.h:782+ / ECUtil.cc:487-729 semantics: a map
shard -> {extent -> bytes} plus the drivers that feed the codec —
``encode`` (parity over page-aligned slices), ``encode_parity_delta``
(delta = old XOR new, applied onto parity via generator columns), and
``decode`` (decode-of-data + re-encode-of-parity split).

TPU-first delta from the reference: the slice iterator batches ALL
slices with the same shard-presence signature into one [S, B, L] device
dispatch instead of a per-4K-slice virtual call — the stripe/slice axis
is the MXU batch axis.

Buffers are host numpy here (this layer is the staging side of the
pipeline); codec calls move them through jax and back.
"""

from __future__ import annotations

import numpy as np

from .extents import ExtentSet
from .hashinfo import HashInfo
from .stripe import PAGE_SIZE, StripeInfo, align_page_next, align_page_prev


class ShardExtentMap:
    """shard -> sorted disjoint (offset, buffer) runs, plus codec drivers."""

    def __init__(self, sinfo: StripeInfo) -> None:
        self.sinfo = sinfo
        self._bufs: dict[int, list[tuple[int, np.ndarray]]] = {}
        #: fused encode+csum output, set by ``encode`` when the kernel
        #: served it: {"block": cb, "shards": {shard: (window_lo,
        #: uint32[nblocks] ZERO-INIT per-block crc32c)}} — the blocks
        #: cover each shard's encode window contiguously
        self.csums: "dict | None" = None

    # -- buffer management --------------------------------------------
    def insert(self, shard: int, offset: int, data) -> None:
        """Insert bytes at a shard offset, coalescing adjacent/overlapping
        runs (later inserts win on overlap, matching extent_map assign)."""
        arr = np.frombuffer(bytes(data), dtype=np.uint8).copy() \
            if isinstance(data, (bytes, bytearray, memoryview)) \
            else np.asarray(data, dtype=np.uint8).reshape(-1).copy()
        if arr.size == 0:
            return
        runs = self._bufs.setdefault(shard, [])
        new_start, new_end = offset, offset + arr.size
        merged_start, merged_end = new_start, new_end
        keep: list[tuple[int, np.ndarray]] = []
        overlapping: list[tuple[int, np.ndarray]] = []
        for off, buf in runs:
            if off + buf.size < merged_start or off > merged_end:
                keep.append((off, buf))
            else:
                overlapping.append((off, buf))
                merged_start = min(merged_start, off)
                merged_end = max(merged_end, off + buf.size)
        out = np.zeros(merged_end - merged_start, dtype=np.uint8)
        for off, buf in overlapping:
            out[off - merged_start : off - merged_start + buf.size] = buf
        out[new_start - merged_start : new_end - merged_start] = arr
        keep.append((merged_start, out))
        keep.sort(key=lambda t: t[0])
        self._bufs[shard] = keep

    def shards(self) -> list[int]:
        return sorted(self._bufs)

    def get_extent_set(self, shard: int) -> ExtentSet:
        return ExtentSet(
            (off, off + buf.size) for off, buf in self._bufs.get(shard, [])
        )

    def get(self, shard: int, offset: int, length: int) -> np.ndarray:
        """Read a range; absent bytes read as zero (the shared
        zero-buffer convention)."""
        out = np.zeros(length, dtype=np.uint8)
        for off, buf in self._bufs.get(shard, []):
            s = max(offset, off)
            e = min(offset + length, off + buf.size)
            if s < e:
                out[s - offset : e - offset] = buf[s - off : e - off]
        return out

    def contains(self, shard: int, offset: int, length: int) -> bool:
        return self.get_extent_set(shard).contains(offset, length)

    def erase_shard(self, shard: int) -> None:
        self._bufs.pop(shard, None)

    def erase(self, shard: int, offset: int, length: int) -> None:
        runs = self._bufs.get(shard)
        if not runs:
            return
        out = []
        for off, buf in runs:
            lo, hi = offset, offset + length
            if off + buf.size <= lo or off >= hi:
                out.append((off, buf))
                continue
            if off < lo:
                out.append((off, buf[: lo - off]))
            if off + buf.size > hi:
                out.append((hi, buf[hi - off :]))
        if out:
            self._bufs[shard] = out
        else:
            del self._bufs[shard]

    # -- geometry helpers ---------------------------------------------
    def ro_range(self) -> tuple[int, int]:
        """(ro_start, ro_end) hull across data shards, stripe-aligned —
        the ro_start/ro_end members of shard_extent_map_t."""
        lo, hi = None, None
        for shard in self._bufs:
            raw = self.sinfo.get_raw_shard(shard)
            if raw >= self.sinfo.k:
                continue
            es = self.get_extent_set(shard)
            if not es:
                continue
            lo = es.range_start() if lo is None else min(lo, es.range_start())
            hi = es.range_end() if hi is None else max(hi, es.range_end())
        if lo is None:
            return 0, 0
        return align_page_prev(lo), align_page_next(hi)

    def pad_and_rebuild_to_page_align(self) -> None:
        """Round every run outward to page boundaries, zero-filling —
        pad_and_rebuild_to_page_align (ECUtil.cc:731): device DMA and
        store writes both want whole pages."""
        for shard in list(self._bufs):
            runs = self._bufs.pop(shard)
            for off, buf in runs:
                start = align_page_prev(off)
                end = align_page_next(off + buf.size)
                padded = np.zeros(end - start, dtype=np.uint8)
                padded[off - start : off - start + buf.size] = buf
                self.insert(shard, start, padded)

    def csums_for(
        self, shard: int, offset: int, length: int
    ) -> "np.ndarray | None":
        """Kernel-produced ZERO-INIT per-block csums covering exactly
        ``[offset, offset+length)`` of ``shard``, or None when the
        fused encode didn't run / the range isn't block-aligned within
        the csum window. What the sub-write generator attaches to each
        store write so BlueStore-analog blob csums come from the
        kernel, not a host re-hash."""
        if self.csums is None:
            return None
        from .stripe import csum_block_range

        entry = self.csums["shards"].get(shard)
        if entry is None:
            return None
        wlo, vals = entry
        rng = csum_block_range(
            offset, length, wlo, int(vals.size), self.csums["block"]
        )
        if rng is None:
            return None
        return vals[rng[0] : rng[1]]

    # -- codec drivers -------------------------------------------------
    def _slice_window(self) -> tuple[int, int]:
        lo, hi = self.ro_range()
        return lo, hi

    def encode(self, codec, hashinfo: HashInfo | None = None,
               old_size: int | None = None,
               csum_block: int | None = None) -> None:
        """Compute parity for every page-aligned slice covered by the
        data shards and insert it into this map (ECUtil.cc:487-511).

        One batched device dispatch per presence-signature, not one per
        slice. Updates ``hashinfo`` with the newly written shard tails
        when given (the encode-time HashInfo append, ECUtil.cc:521-534).

        With ``csum_block`` set and the codec's fused encode+csum
        kernel able to serve the geometry, the SAME dispatch also
        emits per-csum-block crc32c for all k+m shards (recorded in
        ``self.csums`` for the sub-write path to carry to the stores)
        and the HashInfo append is seeded from those kernel csums via
        crc chaining — the bytes are hashed exactly once, on device.
        """
        k, m = self.sinfo.k, self.sinfo.m
        self.csums = None
        lo0, hi0 = self._slice_window()
        if hi0 <= lo0:
            return
        # Chunk-align the dispatch window and batch per chunk: codecs
        # with intra-chunk structure (CLAY sub-chunks) need real chunk
        # boundaries, and the chunk axis is a free MXU batch axis. The
        # HASH window below stays page-aligned (lo0/hi0): hashed size
        # must track what the client wrote so contiguous appends keep
        # extending the cumulative CRCs when chunk_size > PAGE_SIZE.
        cs = self.sinfo.chunk_size
        lo = (lo0 // cs) * cs
        hi = -(-hi0 // cs) * cs
        n_chunks = (hi - lo) // cs
        data = np.stack(
            [
                self.get(self.sinfo.get_shard(r), lo, hi - lo).reshape(
                    n_chunks, cs
                )
                for r in range(k)
            ]
        )
        parity = csums = None
        cb = csum_block
        if (
            cb
            and cs % cb == 0
            and lo % cb == 0
            and hasattr(codec, "encode_chunks_with_csums")
        ):
            # Coalesced/streaming route first: the fused op stages in
            # the ring and shares ONE encode+csum dispatch with every
            # other op of the tick window (the same batching win the
            # plain encode gets below). (None, None) = the fused
            # kernel can't serve the geometry; fall through per-op.
            staged = self._ring_encode_csum(codec, data, cs, cb)
            if staged is not None:
                parity2d, csums = staged
                if parity2d is not None:
                    parity = parity2d.reshape(m, n_chunks, cs)
            if csums is None:
                parity_map, csums = codec.encode_chunks_with_csums(
                    {i: data[i] for i in range(k)}, cb
                )
                if parity_map is not None:
                    parity = np.stack(
                        [np.asarray(parity_map[k + j]) for j in range(m)]
                    )
        if parity is None:
            parity = self._dispatch_encode(codec, data)
        for j in range(m):
            self.insert(
                self.sinfo.get_shard(k + j), lo, parity[j].reshape(-1)
            )
        if csums is not None:
            # [n_chunks, k+m, cs/cb] -> per shard the window's linear
            # block sequence (chunk-major, matching the shard's byte
            # stream at offsets lo + i*cb)
            arr = np.asarray(csums)
            self.csums = {
                "block": cb,
                "shards": {
                    self.sinfo.get_shard(raw): (
                        lo, np.ascontiguousarray(arr[:, raw, :]).reshape(-1)
                    )
                    for raw in range(k + m)
                },
            }
        if hashinfo is not None:
            # Appends must be contiguous and equal-length across shards
            # (the HashInfo contract): hash every shard's zero-padded
            # tail up to the common PAGE window end (not the chunk-
            # aligned dispatch window — see comment above).
            base = lo0 if old_size is None else old_size
            if hi0 > base:
                if (
                    self.csums is not None
                    and base >= lo
                    and (base - lo) % cb == 0
                    and (hi0 - base) % cb == 0
                    and hi0 <= hi
                ):
                    # device-seeded: chain the kernel's zero-init
                    # block csums into the cumulative shard hashes
                    first, last = (base - lo) // cb, (hi0 - lo) // cb
                    hashinfo.append_block_csums(
                        base,
                        {
                            shard: vals[first:last]
                            for shard, (_wlo, vals) in
                            self.csums["shards"].items()
                        },
                        cb,
                    )
                else:
                    hashinfo.append(
                        base,
                        {
                            self.sinfo.get_shard(raw): self.get(
                                self.sinfo.get_shard(raw), base,
                                hi0 - base,
                            )
                            for raw in range(k + m)
                        },
                    )

    @staticmethod
    def _ring_routable(codec, nbytes: int) -> bool:
        """One gate for both ring routes: streaming config on, OR this
        thread is inside a coalesced OSD tick (dispatcher.
        coalescing_scope) — concurrent tick groups stage into the same
        ring window either way. Sub-chunk codecs (CLAY) give chunk
        geometry meaning beyond byte count, and ops beyond a ring slot
        can't stage — both keep the per-op path."""
        from .dispatcher import (
            coalescing_active,
            dispatcher_for,
            streaming_enabled,
        )

        if codec.get_sub_chunk_count() != 1:
            return False
        if not (streaming_enabled() or coalescing_active()):
            return False
        return nbytes <= dispatcher_for(codec).max_op_bytes

    @staticmethod
    def _ring_encode_csum(codec, data, cs: int, cb: int):
        """Stage one fused encode+csum op in the ring, or None when
        the ring isn't routable for it. ``data`` is [k, n_chunks, cs];
        returns ``(parity [m, L] | None, csums | None)``."""
        from .dispatcher import dispatcher_for

        if not ShardExtentMap._ring_routable(codec, data.nbytes):
            return None
        k, n_chunks, _cs = data.shape
        return dispatcher_for(codec).encode_csum_sync(
            np.ascontiguousarray(data).reshape(k, n_chunks * cs),
            cb, n_chunks,
        )

    @staticmethod
    def _dispatch_encode(codec, data: np.ndarray) -> np.ndarray:
        """[k, L] host -> [m, L] host through the codec's dispatch.
        With ``ec_streaming_dispatch`` on — or inside a coalesced OSD
        tick — the op rides the native staging ring and shares a
        batched device dispatch with other concurrent ops
        (pipeline/dispatcher.py)."""
        from .dispatcher import dispatcher_for

        k = data.shape[0]
        flat = data.reshape(k, -1)
        if ShardExtentMap._ring_routable(codec, flat.nbytes):
            return dispatcher_for(codec).encode_sync(flat).reshape(
                (-1,) + data.shape[1:]
            )
        parity = codec.encode_chunks(
            {i: np.asarray(data[i]) for i in range(k)}
        )
        return np.stack(
            [np.asarray(parity[k + j]) for j in range(len(parity))]
        )

    def encode_parity_delta(self, codec, old_map: "ShardExtentMap") -> None:
        """Parity-delta RMW (ECUtil.cc:542-588): for each data shard
        present here, delta = old XOR new; parity' = parity XOR
        sum_i G[:,i] * delta_i. ``old_map`` must hold the old data AND
        old parity over this map's window."""
        from ceph_tpu.codecs.interface import Flag

        k, m = self.sinfo.k, self.sinfo.m
        lo, hi = self._slice_window()
        if hi <= lo:
            return
        # Packet-layout codes need chunk-shaped delta windows: the
        # packet decomposition is per-chunk, so the window is widened
        # to chunk boundaries and every buffer reshaped [n_chunks, cs]
        # (delta outside the written extents is zero by construction,
        # and the planner chunk-aligned the parity reads/writes).
        chunk_gran = bool(
            codec.get_flags() & Flag.PARITY_DELTA_CHUNK_GRANULARITY
        )
        if chunk_gran:
            cs = self.sinfo.chunk_size
            lo = (lo // cs) * cs
            hi = -(-hi // cs) * cs
            shape = ((hi - lo) // cs, cs)
        deltas = {}
        for raw in range(k):
            shard = self.sinfo.get_shard(raw)
            if shard not in self._bufs:
                continue
            # Only bytes this map actually wrote may differ: fill the
            # rest of the window from old so delta is zero there (a
            # zero-filled gap would otherwise XOR the old data OUT of
            # the parity — silent corruption).
            old = old_map.get(shard, lo, hi - lo)
            new = old.copy()
            for off, end in self.get_extent_set(shard):
                s = max(off, lo)
                e = min(end, hi)
                if s < e:
                    new[s - lo : e - lo] = self.get(shard, s, e - s)
            # delta is plain GF addition: XOR on the host (a device
            # round-trip per shard would serialize k tunnel RTTs)
            d = np.bitwise_xor(np.asarray(old), np.asarray(new))
            deltas[raw] = d.reshape(shape) if chunk_gran else d
        if not deltas:
            return
        parity_in = {}
        for j in range(m):
            p = np.asarray(
                old_map.get(self.sinfo.get_shard(k + j), lo, hi - lo)
            )
            parity_in[k + j] = p.reshape(shape) if chunk_gran else p
        parity_out = codec.apply_delta(deltas, parity_in)
        for j in range(m):
            self.insert(
                self.sinfo.get_shard(k + j), lo,
                np.asarray(parity_out[k + j]).reshape(-1),
            )

    def decode(self, codec, want: set[int], object_size: int) -> None:
        """Reconstruct the wanted shards from whatever this map holds
        (ECUtil.cc:648-729): wanted data shards decode from any k
        survivors; wanted parity shards re-encode from (possibly just-
        decoded) data. Buffers are zero-padded to the common window and
        trimmed back to each shard's size within ``object_size``."""
        sinfo = self.sinfo
        missing_raw = sorted(
            sinfo.get_raw_shard(s) for s in want if s not in self._bufs
        )
        if not missing_raw:
            return
        cs = sinfo.chunk_size
        hull = sinfo.chunk_aligned_hull(
            self.get_extent_set(shard) for shard in self._bufs
        )
        if hull is None or hull[1] <= hull[0]:
            return
        lo, hi = hull
        # a wanted shard that STORES nothing in the window (short
        # object / post-truncate tail) needs no reconstruction — its
        # bytes are zeros by convention; demanding k survivors for it
        # would fail exactly when the object is small. It must still
        # MATERIALIZE as zeros here: callers (the RMW extent cache)
        # check that requested extents became present, and an absent
        # shard would re-issue the backend read forever.
        zero_raw = [
            raw for raw in missing_raw
            if sinfo.object_size_to_exact_shard_size(
                object_size, sinfo.get_shard(raw)
            ) <= lo
        ]
        for raw in zero_raw:
            shard = sinfo.get_shard(raw)
            end = min(
                hi, sinfo.object_size_to_shard_size(object_size, shard)
            )
            if end > lo:
                self.insert(
                    shard, lo, np.zeros(end - lo, dtype=np.uint8)
                )
        missing_raw = [r for r in missing_raw if r not in zero_raw]
        if not missing_raw:
            return
        # Survivors must cover the stored part of the window: a shard
        # holding only a sub-range would decode zero-filled gaps into
        # the output (absent bytes are zero ONLY beyond shard size).
        # EXACT size, not the page-rounded one: codecs whose chunk is
        # not a page multiple (liberation family, chunk = w * align)
        # store data shards to the exact tail — the page-rounding gap
        # is zeros by convention, not missing bytes.
        present_raw = []
        for shard in self._bufs:
            ssize = sinfo.object_size_to_exact_shard_size(object_size, shard)
            end = min(hi, ssize)
            if end <= lo or self.get_extent_set(shard).contains(lo, end - lo):
                present_raw.append(sinfo.get_raw_shard(shard))
        # a shard NOT in the map whose stored size ends at/before the
        # window is a KNOWN-ZERO survivor (short object / truncated
        # tail): its window content is zeros by convention, and
        # counting it can be the difference between decodable and not
        # (e.g. two lost shards + one empty shard in a k=4 stripe)
        for raw in range(sinfo.k + sinfo.m):
            shard = sinfo.get_shard(raw)
            if shard in self._bufs or raw in missing_raw:
                continue
            if sinfo.object_size_to_exact_shard_size(
                object_size, shard
            ) <= lo:
                present_raw.append(raw)
        present_raw.sort()
        n_chunks = (hi - lo) // cs
        chunks = {
            raw: np.asarray(
                self.get(sinfo.get_shard(raw), lo, hi - lo).reshape(
                    n_chunks, cs
                )
            )
            for raw in present_raw
        }
        out = codec.decode_chunks(set(missing_raw), chunks)
        for raw in missing_raw:
            shard = sinfo.get_shard(raw)
            buf = np.asarray(out[raw]).reshape(-1)
            shard_size = sinfo.object_size_to_shard_size(object_size, shard)
            end = min(hi, shard_size)
            if end > lo:
                self.insert(shard, lo, buf[: end - lo])

    # -- debug ---------------------------------------------------------
    def __repr__(self) -> str:
        parts = ", ".join(
            f"{s}:{self.get_extent_set(s)!r}" for s in self.shards()
        )
        return f"ShardExtentMap({parts})"
