"""Interval arithmetic for shard extents.

The reference threads ``extent_set``/``extent_map`` (interval containers
over byte offsets) through every EC read/write plan
(src/osd/ECUtil.h:202-344 ``shard_extent_set_t``). Here extents are
host-side shape math: they decide what to DMA and how to tile kernels,
and never reach the device.
"""

from __future__ import annotations

from bisect import bisect_right
from collections.abc import Iterable, Iterator


class ExtentSet:
    """Sorted, coalesced set of half-open byte ranges [start, end)."""

    __slots__ = ("_runs",)

    def __init__(self, runs: Iterable[tuple[int, int]] = ()) -> None:
        self._runs: list[tuple[int, int]] = []
        for start, end in runs:
            self.insert(start, end - start)

    # -- mutation ------------------------------------------------------
    def insert(self, offset: int, length: int) -> None:
        if length <= 0:
            return
        start, end = offset, offset + length
        runs = self._runs
        i = bisect_right(runs, (start,)) - 1
        if i >= 0 and runs[i][1] >= start:
            start = runs[i][0]
        else:
            i += 1
        j = i
        while j < len(runs) and runs[j][0] <= end:
            end = max(end, runs[j][1])
            j += 1
        runs[i:j] = [(start, end)]

    def union(self, other: "ExtentSet") -> None:
        for start, end in other._runs:
            self.insert(start, end - start)

    def erase(self, offset: int, length: int) -> None:
        if length <= 0:
            return
        start, end = offset, offset + length
        out = []
        for s, e in self._runs:
            if e <= start or s >= end:
                out.append((s, e))
                continue
            if s < start:
                out.append((s, start))
            if e > end:
                out.append((end, e))
        self._runs = out

    # -- queries -------------------------------------------------------
    def __iter__(self) -> Iterator[tuple[int, int]]:
        return iter(self._runs)

    def __len__(self) -> int:
        return len(self._runs)

    def __bool__(self) -> bool:
        return bool(self._runs)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ExtentSet) and self._runs == other._runs

    def __repr__(self) -> str:
        spans = ",".join(f"[{s},{e})" for s, e in self._runs)
        return f"ExtentSet({spans})"

    def size(self) -> int:
        return sum(e - s for s, e in self._runs)

    def range_start(self) -> int:
        return self._runs[0][0]

    def range_end(self) -> int:
        return self._runs[-1][1]

    def contains(self, offset: int, length: int = 1) -> bool:
        i = bisect_right(self._runs, (offset,)) - 1
        if i >= 0 and self._runs[i][1] >= offset + length:
            return True
        # bisect on (offset,) sorts before (offset, end): check the run
        # actually starting at `offset` too.
        i += 1
        return (
            i < len(self._runs)
            and self._runs[i][0] <= offset
            and self._runs[i][1] >= offset + length
        )

    def intersects(self, offset: int, length: int) -> bool:
        end = offset + length
        i = bisect_right(self._runs, (offset,)) - 1
        for s, e in self._runs[max(i, 0):]:
            if s >= end:
                return False
            if e > offset:
                return True
        return False

    def intersection(self, other: "ExtentSet") -> "ExtentSet":
        out = ExtentSet()
        a, b = self._runs, other._runs
        i = j = 0
        while i < len(a) and j < len(b):
            s = max(a[i][0], b[j][0])
            e = min(a[i][1], b[j][1])
            if s < e:
                out.insert(s, e - s)
            if a[i][1] < b[j][1]:
                i += 1
            else:
                j += 1
        return out

    def difference(self, other: "ExtentSet") -> "ExtentSet":
        out = ExtentSet(self._runs)
        for s, e in other._runs:
            out.erase(s, e - s)
        return out

    def copy(self) -> "ExtentSet":
        c = ExtentSet()
        c._runs = list(self._runs)
        return c

    def align(self, granularity: int) -> "ExtentSet":
        """Widen every run outward to multiples of ``granularity`` (the
        page/chunk rounding the reference applies before device work)."""
        out = ExtentSet()
        for s, e in self._runs:
            s2 = (s // granularity) * granularity
            e2 = -(-e // granularity) * granularity
            out.insert(s2, e2 - s2)
        return out
