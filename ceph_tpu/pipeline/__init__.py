"""Stripe pipeline: the OSD EC data-path semantics over batched TPU dispatch.

Mirrors the role of the reference's osd/EC* stack (SURVEY.md section 2.2):
``stripe`` is the ECUtil analog (geometry + shard extent maps + HashInfo),
``transaction`` the ECTransaction analog (write planning), ``cache`` the
ECExtentCache analog, ``rmw``/``read`` the ECCommon pipelines, ``store``
the MemStore-style shard store, and ``recovery`` the backfill FSM.
"""

from .extents import ExtentSet
from .hashinfo import HashInfo
from .stripe import StripeInfo
from .shard_map import ShardExtentMap
from .read import ReadPipeline, ShardReadError
from .recovery import RecoveryBackend, RecoveryState, be_deep_scrub
from .pglog import PGLog

__all__ = [
    "ExtentSet",
    "HashInfo",
    "StripeInfo",
    "ShardExtentMap",
    "ReadPipeline",
    "ShardReadError",
    "RecoveryBackend",
    "RecoveryState",
    "be_deep_scrub",
    "PGLog",
]
