"""Streaming dispatcher: the native staging ring feeding batched
device dispatches — SURVEY.md §7 step 4 assembled (host ring ->
staging -> batched device dispatch -> completion callbacks).

The role it fills is the reference's sharded op queues
(osd/OSD.cc:9874-9933): many client ops across many PGs land on a
shared queue and drain in batches. Here the batching axis IS the TPU
win: one [B, k, L] device encode amortizes the per-dispatch launch
(and, through a remote-device tunnel, the round trip) over every
small op in the batch — the per-op path pays it per 4-64 KiB write.

Shape of the machinery:

- producers (OSD daemons, RMW pipelines, any thread) ``submit()``
  ops into the native MPMC ring (native/src/ceph_tpu_native.cc,
  ``ctpu_ring_*``) as header+payload slots; the ring is the
  bounded staging tier — backpressure is a blocking push;
- ONE dispatcher thread drains the ring: it blocks for the first op,
  then keeps popping until the ring is momentarily empty past the
  batching window or ``max_batch`` is reached;
- ops group by (k, chunk_len) signature; each group stacks into one
  [B, k, L] batch, encodes through the codec's normal dispatch
  (device kernel / mesh / einsum — the codec router decides), and
  completion callbacks fire with each op's parity rows;
- ``encode_sync`` is the synchronous facade for pipeline callers:
  submit + wait, with concurrency across threads supplying the batch.

The round-10 serving tier adds three seams:

- ``coalescing_scope()`` — a thread-local scope the OSD daemon's
  coalesced tick batch enters around each PG group's execution:
  inside it, ``ShardExtentMap`` routes encodes through the ring even
  when ``ec_streaming_dispatch`` is off, so concurrent groups of one
  tick share batched device dispatches;
- fused encode+csum ops stage through the SAME ring (``submit`` with
  ``csum_block``): a fused group stacks every member's chunks into
  one ``encode_chunks_with_csums`` dispatch — the whole coalesced
  tick pays one HBM pass for data, parity AND block csums;
- per-op error isolation: a failed MULTI-op batch no longer fails
  every member — each op retries SOLO through the codec, and only
  the op that actually faults surfaces its error (``solo_retries`` /
  ``batch_faults`` counters). One poisoned op cannot sink its
  batch-mates.

Counters (``perf dump`` section ``ec_stream``): ops, batches,
batched_ops (ops that shared a dispatch), plus a max-batch gauge,
batch_faults (multi-op dispatches that failed and split), and
solo_retries (ops that recovered via solo fallback).
"""

from __future__ import annotations

import contextlib
import functools
import struct
import threading
import time
from collections import defaultdict
from collections.abc import Callable

import numpy as np
from ceph_tpu.utils import lockdep
from ceph_tpu.utils.lockdep import DebugLock

#: slot header: op id, k, chunk count, chunk size, csum block
#: (csum block 0 = plain encode; then the payload is [k, n*cs] flat)
_HDR = struct.Struct("<QHHII")


@functools.lru_cache(maxsize=1)
def _stream_counters():
    from ceph_tpu.utils.perf_counters import (
        PerfCountersBuilder,
        perf_collection,
    )

    b = PerfCountersBuilder(perf_collection, "ec_stream")
    b.add_u64_counter("ops", "ops submitted to the streaming dispatcher")
    b.add_u64_counter("batches", "device dispatches issued")
    b.add_u64_counter(
        "batched_ops", "ops that shared a dispatch with at least one other"
    )
    b.add_u64_gauge("max_batch", "largest batch assembled (high-water)")
    b.add_u64_counter(
        "batch_faults", "multi-op dispatches that failed and split"
    )
    b.add_u64_counter(
        "solo_retries", "ops recovered via solo fallback after a "
        "batch fault"
    )
    return b.create_perf_counters()


# ------------------------------------------------------- coalescing scope
_coal_tls = threading.local()


@contextlib.contextmanager
def coalescing_scope():
    """Thread-local scope marking this thread's encodes as part of a
    coalesced tick batch (the OSD daemon enters it around each PG
    group of a wave). Inside it, the shard-map encode routes through
    the streaming ring regardless of ``ec_streaming_dispatch`` —
    concurrent group threads of one tick land their ops in the same
    ring window and share batched device dispatches. Nesting-safe."""
    _coal_tls.depth = getattr(_coal_tls, "depth", 0) + 1
    try:
        yield
    finally:
        _coal_tls.depth -= 1


def coalescing_active() -> bool:
    """True on a thread currently inside ``coalescing_scope`` (with
    the native ring present to stage into)."""
    if getattr(_coal_tls, "depth", 0) <= 0:
        return False
    from ceph_tpu import native

    return native.available()


class StreamingDispatcher:
    """Aggregates concurrent small encodes into batched dispatches."""

    def __init__(
        self,
        codec,
        *,
        capacity: int = 128,
        slot_bytes: int = (256 << 10) + _HDR.size,
        max_batch: int = 128,
        window_s: float = 0.0005,
    ) -> None:
        # Defaults size the ring for its small-op mission (the native
        # ring allocates capacity*slot_bytes EAGERLY — 32 MiB here,
        # not the 512 MiB a 1 MiB slot would pin); oversized ops take
        # the per-op path (see max_op_bytes / shard_map routing).
        from ceph_tpu.native import RingBuffer

        self.codec = codec
        self.max_batch = max_batch
        self.window_s = window_s
        self._ring = RingBuffer(capacity, slot_bytes)
        self._slot_payload = slot_bytes - _HDR.size
        self._lock = DebugLock("dispatcher.ring")
        self._next_id = 0
        #: op id -> (callback, k, chunk_len)
        self._pending: dict[int, tuple[Callable, int, int]] = {}
        self._closed = False
        self._thread = threading.Thread(
            target=self._drain_loop, name="ec-stream", daemon=True
        )
        self._thread.start()

    @property
    def max_op_bytes(self) -> int:
        """Largest [k, L] payload one slot can stage."""
        return self._slot_payload

    # -- producer side --------------------------------------------------
    def submit(
        self,
        data: np.ndarray,
        callback: Callable[[np.ndarray], None],
        csum_block: int = 0,
        n_chunks: int = 1,
    ) -> int:
        """Queue one encode of ``data`` [k, L] uint8; ``callback``
        fires (dispatcher thread) with the parity [m, L].

        With ``csum_block`` > 0 the op is a FUSED encode+csum: ``L``
        is ``n_chunks * chunk_size`` (chunk-major per shard) and the
        callback receives ``(parity [m, L], csums [n_chunks, k+m,
        cs/cb])`` — or ``(None, None)`` when no fused kernel route
        serves the geometry (callers keep their per-op fallback)."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if data.ndim != 2:
            raise ValueError(f"want [k, L], got {data.shape}")
        k, ln = data.shape
        if k * ln > self._slot_payload:
            raise ValueError(
                f"op {k}x{ln} exceeds slot payload {self._slot_payload}"
            )
        if ln % max(n_chunks, 1):
            raise ValueError(f"L={ln} not divisible into {n_chunks}")
        with self._lock:
            if self._closed:
                raise RuntimeError("dispatcher stopped")
            op_id = self._next_id
            self._next_id += 1
            self._pending[op_id] = (callback, k, ln)
        slot = (
            _HDR.pack(op_id, k, n_chunks, ln // max(n_chunks, 1),
                      csum_block)
            + data.tobytes()
        )
        if not self._ring.push(slot, blocking=True):
            # the ring refused the slot (closed by a concurrent
            # stop()): fail loudly — a silent drop would wedge the
            # encode_sync waiter forever
            with self._lock:
                self._pending.pop(op_id, None)
            raise RuntimeError("dispatcher stopped")
        _stream_counters().inc("ops")
        return op_id

    def encode_sync(self, data: np.ndarray) -> np.ndarray:
        """Submit + wait; the batch forms from OTHER threads' ops
        arriving inside the window. A codec failure for the batch
        re-raises here (the callback receives the exception)."""
        out = self._submit_wait(data, 0, 1)
        return out

    def encode_csum_sync(
        self, data: np.ndarray, csum_block: int, n_chunks: int
    ):
        """Fused submit + wait: ``data`` [k, n_chunks*cs] chunk-major;
        returns ``(parity [m, L], csums [n_chunks, k+m, cs/cb])`` or
        ``(None, None)`` when the fused kernel can't serve the
        geometry."""
        return self._submit_wait(data, csum_block, n_chunks)

    def _submit_wait(self, data, csum_block, n_chunks):
        ev = threading.Event()
        out: list = []

        def cb(result) -> None:
            out.append(result)
            ev.set()

        self.submit(data, cb, csum_block=csum_block, n_chunks=n_chunks)
        # lockdep checkpoint: waiting out a batched device dispatch is
        # a blocking call (the "dispatcher.submit_wait" waiver covers
        # the op path's own encode work)
        with lockdep.blocking_region("dispatcher.submit_wait"):
            ev.wait()
        if isinstance(out[0], BaseException):
            raise out[0]
        return out[0]

    # -- dispatcher thread ----------------------------------------------
    def _drain_loop(self) -> None:
        while True:
            first = self._ring.pop(blocking=True)
            if first is None:  # closed and drained
                return
            ops = [first]
            # Self-clocking batch assembly (deadline + occupancy
            # hybrid, round 4): drain whatever is ALREADY queued, then
            # fire the moment the ring runs empty — waiting out the
            # window only added latency, because the next batch forms
            # naturally from the backlog that accumulates while THIS
            # dispatch is on the device (arrival rate x service time).
            # The window now only bounds a torn burst: producers
            # observed mid-enqueue get one short grace period instead
            # of a full window.
            deadline = time.monotonic() + self.window_s
            grace_used = False
            while len(ops) < self.max_batch:
                nxt = self._ring.pop(blocking=False)
                if nxt is not None:
                    ops.append(nxt)
                    continue
                if grace_used or time.monotonic() >= deadline:
                    break
                grace_used = True
                time.sleep(0.00005)
            try:
                self._fire(ops)
            except Exception:
                # The drain thread must survive ANYTHING — a dead
                # drain wedges every producer on the full ring. _fire
                # already routes per-group failures to callbacks; this
                # catches bookkeeping bugs.
                from ceph_tpu.utils.log import get_logger

                get_logger("ec-stream").error(
                    "drain iteration failed; continuing"
                )

    def _fire(self, slots: list[bytes]) -> None:
        pc = _stream_counters()
        #: plain encodes group by flat shape; fused group by chunk
        #: geometry + csum block (members stack on the chunk axis)
        plain: dict[tuple[int, int], list[tuple[int, np.ndarray]]] = (
            defaultdict(list)
        )
        fused: dict[
            tuple[int, int, int], list[tuple[int, int, np.ndarray]]
        ] = defaultdict(list)
        for raw in slots:
            op_id, k, nc, cs, cb = _HDR.unpack_from(raw)
            ln = nc * cs
            payload = np.frombuffer(
                raw, np.uint8, count=k * ln, offset=_HDR.size
            ).reshape(k, ln)
            if cb:
                fused[(k, cs, cb)].append((op_id, nc, payload))
            else:
                plain[(k, ln)].append((op_id, payload))
        for (k, ln), members in plain.items():
            results = self._fire_plain(pc, k, members)
            self._deliver(members, results)
        for (k, cs, cb), fmembers in fused.items():
            results = self._fire_fused(pc, k, cs, cb, fmembers)
            self._deliver(fmembers, results)

    def _fire_plain(self, pc, k, members) -> list:
        try:
            stacked = np.stack([p for _, p in members])  # [B, k, L]
            parity = self.codec.encode_chunks(
                {i: stacked[:, i, :] for i in range(k)}
            )
            m = len(parity)
            out = np.stack(
                [np.asarray(parity[k + j]) for j in range(m)],
                axis=1,
            )  # [B, m, L]
            results: list = [out[i] for i in range(len(members))]
            pc.inc("batches")
            if len(members) > 1:
                pc.inc("batched_ops", len(members))
            if len(members) > pc.get("max_batch"):
                pc.set("max_batch", len(members))
            return results
        except Exception as e:
            return self._solo_fallback(
                pc, members, e,
                lambda payload: self._encode_one(k, payload),
            )

    def _encode_one(self, k: int, payload: np.ndarray) -> np.ndarray:
        parity = self.codec.encode_chunks(
            {i: payload[None, i, :] for i in range(k)}
        )
        return np.stack(
            [np.asarray(parity[k + j])[0] for j in range(len(parity))]
        )

    def _fire_fused(self, pc, k, cs, cb, members) -> list:
        """One fused encode+csum dispatch for the whole group: every
        member's chunks stack on the batch axis, so the coalesced
        tick's data, parity and block csums are one HBM pass. A
        ``(None, None)`` kernel answer (geometry unservable) is a
        clean per-member result — callers fall back per-op."""

        def one(payload: np.ndarray):
            nc = payload.shape[1] // cs
            chunks = payload.reshape(k, nc, cs).transpose(1, 0, 2)
            pm, csums = self.codec.encode_chunks_with_csums(
                {i: chunks[:, i, :] for i in range(k)}, cb
            )
            if pm is None:
                return (None, None)
            m = len(pm)
            out = np.stack(
                [np.asarray(pm[k + j]) for j in range(m)], axis=1
            )  # [nc, m, cs]
            return (
                out.transpose(1, 0, 2).reshape(m, nc * cs),
                np.asarray(csums),
            )

        try:
            counts = [nc for _, nc, _ in members]
            stacked = np.concatenate(
                [
                    p.reshape(k, nc, cs).transpose(1, 0, 2)
                    for _, nc, p in members
                ],
                axis=0,
            )  # [sum(nc), k, cs]
            pm, csums = self.codec.encode_chunks_with_csums(
                {i: stacked[:, i, :] for i in range(k)}, cb
            )
            if pm is None:
                return [(None, None)] * len(members)
            m = len(pm)
            out = np.stack(
                [np.asarray(pm[k + j]) for j in range(m)], axis=1
            )  # [sum(nc), m, cs]
            csums = np.asarray(csums)
            results: list = []
            pos = 0
            for nc in counts:
                sl = out[pos : pos + nc]  # [nc, m, cs]
                results.append((
                    sl.transpose(1, 0, 2).reshape(m, nc * cs),
                    csums[pos : pos + nc],
                ))
                pos += nc
            pc.inc("batches")
            if len(members) > 1:
                pc.inc("batched_ops", len(members))
            if len(members) > pc.get("max_batch"):
                pc.set("max_batch", len(members))
            return results
        except Exception as e:
            return self._solo_fallback(
                pc, members, e, lambda payload: one(payload)
            )

    def _solo_fallback(self, pc, members, batch_err, one) -> list:
        """Per-op error isolation: a failed MULTI-op dispatch retries
        each member solo so one poisoned op cannot fail its
        batch-mates; a solo failure delivers the error to that member
        alone (a waiting encode_sync re-raises it; nobody hangs)."""
        if len(members) == 1:
            return [batch_err]
        pc.inc("batch_faults")
        results: list = []
        for member in members:
            payload = member[-1]
            try:
                results.append(one(payload))
                pc.inc("solo_retries")
            except Exception as solo_err:
                results.append(solo_err)
        return results

    def _deliver(self, members, results) -> None:
        for idx, member in enumerate(members):
            op_id = member[0]
            with self._lock:
                cb, _, _ = self._pending.pop(op_id)
            try:
                cb(results[idx])
            except Exception:
                from ceph_tpu.utils.log import get_logger

                get_logger("ec-stream").error(
                    "completion callback raised for op", op_id
                )

    # -- lifecycle -------------------------------------------------------
    def stop(self) -> None:
        with self._lock:
            self._closed = True
        self._ring.close()
        self._thread.join(timeout=5)


# ---------------------------------------------------------------- routing
_global: dict[tuple, StreamingDispatcher] = {}
_global_lock = DebugLock("dispatcher.registry")


def _codec_signature(codec) -> tuple:
    """Batching identity: two codecs with the same signature produce
    identical parity, so their ops may share a dispatcher (and a
    batch). Keyed by class + geometry + the encode matrix bytes when
    available — NOT instance id: PG objects rebuild their codecs on
    every map change, and an id-keyed cache would leak one ring +
    thread per rebuild while never batching across PGs."""
    bmat = getattr(codec, "_encode_bmat_np", None)
    return (
        type(codec).__name__,
        getattr(codec, "k", 0),
        getattr(codec, "m", 0),
        bmat.tobytes() if bmat is not None else None,
    )


def dispatcher_for(codec) -> StreamingDispatcher:
    """Shared dispatcher per codec SIGNATURE (lazily created) — the
    seam ShardExtentMap uses when ``ec_streaming_dispatch`` is on.
    Ops from every PG with the same EC profile share one ring and
    batch together."""
    key = _codec_signature(codec)
    with _global_lock:
        d = _global.get(key)
        if d is None:
            d = StreamingDispatcher(codec)
            _global[key] = d
        return d


def streaming_enabled() -> bool:
    from ceph_tpu.utils import config

    if not config.get("ec_streaming_dispatch"):
        return False
    from ceph_tpu import native

    return native.available()


def shutdown_all() -> None:
    with _global_lock:
        for d in _global.values():
            d.stop()
        _global.clear()
