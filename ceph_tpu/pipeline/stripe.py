"""Stripe geometry — the ``stripe_info_t`` analog.

Behavioral mirror of osd/ECUtil.h:346-729: rados-object offsets
("ro offsets") map onto k data shards round-robin by chunk; parity
shards trail; an optional ``chunk_mapping`` permutes logical ("raw")
shard positions to stored shard ids. All of this is host-side integer
shape math — on TPU the stripe axis becomes the batch dimension of one
kernel dispatch, so getting this arithmetic right IS the data layout.

Vocabulary (matches the reference):
- ``raw_shard``: logical position 0..k-1 data, k..k+m-1 parity.
- ``shard``: stored position, ``chunk_mapping[raw_shard]``.
- ``ro_offset``: byte offset in the rados object.
- ``shard_offset``: byte offset within one shard's store.
"""

from __future__ import annotations

from .extents import ExtentSet

# BlueStore writes whole pages; the reference aligns shard IO to 4K
# (ECUtil.h align_page_next). Device tiling wants the same.
PAGE_SIZE = 4096


def align_page_next(x: int) -> int:
    return -(-x // PAGE_SIZE) * PAGE_SIZE


def align_page_prev(x: int) -> int:
    return (x // PAGE_SIZE) * PAGE_SIZE


def csum_block_range(
    offset: int,
    length: int,
    window_lo: int,
    nblocks: int,
    csum_block: int,
) -> "tuple[int, int] | None":
    """Block-index [first, last) of ``[offset, offset+length)`` within
    a csum window starting at ``window_lo`` that holds ``nblocks``
    blocks of ``csum_block`` bytes — or None unless the range is
    exactly block-aligned and fully covered. The shared shape math
    that lets fused-kernel csums travel with sub-writes: a store may
    only adopt kernel csums for ranges they describe bit-for-bit."""
    if length <= 0 or csum_block <= 0 or offset < window_lo:
        return None
    rel = offset - window_lo
    if rel % csum_block or length % csum_block:
        return None
    first = rel // csum_block
    last = first + length // csum_block
    if last > nblocks:
        return None
    return first, last


class StripeInfo:
    """Geometry of one EC pool: (k, m, stripe_width, chunk_mapping).

    ``stripe_width`` must be a multiple of k; ``chunk_size`` =
    stripe_width / k (ECUtil.h:418).
    """

    def __init__(
        self,
        k: int,
        m: int,
        stripe_width: int,
        chunk_mapping: list[int] | None = None,
    ) -> None:
        if stripe_width <= 0 or stripe_width % k != 0:
            raise ValueError(
                f"stripe_width {stripe_width} must be a positive multiple of k={k}"
            )
        self.k = k
        self.m = m
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // k
        mapping = list(chunk_mapping or [])
        # complete_chunk_mapping semantics (ECUtil.h:370-382): identity
        # beyond the provided prefix.
        for i in range(len(mapping), k + m):
            mapping.append(i)
        mapping = mapping[: k + m]
        rev: list[int] = [-1] * (k + m)
        for raw, shard in enumerate(mapping):
            if rev[shard] != -1:
                raise ValueError(f"chunk_mapping not a permutation: {mapping}")
            rev[shard] = raw
        self.chunk_mapping = mapping
        self.chunk_mapping_reverse = rev
        self.data_shards = frozenset(mapping[:k])
        self.parity_shards = frozenset(mapping[k:])

    # -- shard id translation -----------------------------------------
    def get_shard(self, raw_shard: int) -> int:
        return self.chunk_mapping[raw_shard]

    def get_raw_shard(self, shard: int) -> int:
        return self.chunk_mapping_reverse[shard]

    def is_data_shard(self, shard: int) -> bool:
        return shard in self.data_shards

    def is_parity_shard(self, shard: int) -> bool:
        return shard in self.parity_shards

    # -- offset arithmetic (ECUtil.h:499-663) -------------------------
    def ro_offset_to_shard_offset(self, ro_offset: int, raw_shard: int) -> int:
        """Shard-local offset of ``ro_offset`` as seen by ``raw_shard``
        (ECUtil.h:517-529): full stripes contribute chunk_size each;
        within the current stripe, shards before the offset's chunk are
        full, later ones empty."""
        full = (ro_offset // self.stripe_width) * self.chunk_size
        offset_shard = (ro_offset // self.chunk_size) % self.k
        if raw_shard == offset_shard:
            return full + ro_offset % self.chunk_size
        if raw_shard < offset_shard:
            return full + self.chunk_size
        return full

    def object_size_to_shard_size(self, size: int, shard: int) -> int:
        """Stored bytes on ``shard`` for an object of ``size`` bytes,
        page-aligned (ECUtil.h:499-515). Parity shards match data
        shard 0 (they exist for every written stripe)."""
        remainder = size % self.stripe_width
        shard_size = (size - remainder) // self.k
        raw = self.get_raw_shard(shard)
        if raw >= self.k:
            raw = 0
        skip = raw * self.chunk_size
        if remainder > skip:
            shard_size += min(remainder - skip, self.chunk_size)
        return align_page_next(shard_size)

    def ro_offset_to_prev_stripe_ro_offset(self, ro_offset: int) -> int:
        return (ro_offset // self.stripe_width) * self.stripe_width

    def ro_offset_to_next_stripe_ro_offset(self, ro_offset: int) -> int:
        return -(-ro_offset // self.stripe_width) * self.stripe_width

    def ro_offset_to_prev_chunk_offset(self, ro_offset: int) -> int:
        return (ro_offset // self.stripe_width) * self.chunk_size

    def ro_offset_to_next_chunk_offset(self, ro_offset: int) -> int:
        return -(-ro_offset // self.stripe_width) * self.chunk_size

    def chunk_aligned_ro_range_to_shard_ro_range(
        self, ro_offset: int, ro_length: int
    ) -> tuple[int, int]:
        """Stripe-align an ro range, then express it per shard: every
        shard sees [off/k, len/k) of the aligned range (ECUtil.h:644)."""
        start = self.ro_offset_to_prev_stripe_ro_offset(ro_offset)
        end = self.ro_offset_to_next_stripe_ro_offset(ro_offset + ro_length)
        return start // self.k, (end - start) // self.k

    # -- range fan-out -------------------------------------------------
    def ro_range_to_shard_extent_set(
        self, ro_offset: int, ro_length: int, parity: bool = False
    ) -> dict[int, ExtentSet]:
        """Per-shard extents touched by the ro byte range
        (ECUtil.h:665-695). With ``parity=True`` parity shards get the
        chunk-aligned hull (every touched stripe writes all parity)."""
        out: dict[int, ExtentSet] = {}
        if ro_length <= 0:
            return out
        end = ro_offset + ro_length
        pos = ro_offset
        while pos < end:
            chunk_index = pos // self.chunk_size
            raw_shard = chunk_index % self.k
            in_chunk = pos % self.chunk_size
            take = min(self.chunk_size - in_chunk, end - pos)
            shard = self.get_shard(raw_shard)
            shard_off = (chunk_index // self.k) * self.chunk_size + in_chunk
            out.setdefault(shard, ExtentSet()).insert(shard_off, take)
            pos += take
        if parity:
            first = self.ro_offset_to_prev_chunk_offset(ro_offset)
            last = self.ro_offset_to_next_chunk_offset(end)
            for raw in range(self.k, self.k + self.m):
                out.setdefault(self.get_shard(raw), ExtentSet()).insert(
                    first, last - first
                )
        return out

    def object_size_to_exact_shard_size(self, size: int, shard: int) -> int:
        """Bytes the write path actually stores on ``shard``: data
        shards keep the exact (unpadded) tail; parity shards are
        written for every touched page, so they stay page-aligned."""
        raw = self.get_raw_shard(shard)
        if raw >= self.k:
            return self.object_size_to_shard_size(size, shard)
        remainder = size % self.stripe_width
        shard_size = (size - remainder) // self.k
        skip = raw * self.chunk_size
        if remainder > skip:
            shard_size += min(remainder - skip, self.chunk_size)
        return shard_size

    def chunk_aligned_hull(self, extent_sets) -> tuple[int, int] | None:
        """Chunk-aligned [lo, hi) hull over shard-offset extent sets —
        the window every decode/encode dispatch covers. None if empty."""
        cs = self.chunk_size
        lo = hi = None
        for es in extent_sets:
            if not es:
                continue
            s0 = (es.range_start() // cs) * cs
            e0 = -(-es.range_end() // cs) * cs
            lo = s0 if lo is None else min(lo, s0)
            hi = e0 if hi is None else max(hi, e0)
        if lo is None:
            return None
        return lo, hi

    def __repr__(self) -> str:
        return (
            f"StripeInfo(k={self.k}, m={self.m}, "
            f"stripe_width={self.stripe_width}, "
            f"chunk_size={self.chunk_size})"
        )
