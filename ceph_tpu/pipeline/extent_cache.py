"""Write-back stripe cache for partial-write RMW — the ``ECExtentCache``
analog (osd/ECExtentCache.h:4-74, 863 LoC).

Semantics kept from the reference's design note:

- Per-object cached shard extents, organised into fixed-size cache
  *lines* (32K per shard) tracked by a shared LRU; lines referenced by
  in-flight ops are pinned and unevictable.
- At most ONE outstanding backend read at a time (per PG in the
  reference; per cache instance here) — reads for later ops queue.
- IO is never reordered: an op's ready callback fires only after every
  earlier op on the same object has fired, even if its data arrived
  first.
- ``write_done`` publishes the just-written buffers back into the cache
  so immediately-following partial writes of the same stripe hit.

Event-driven and single-threaded by design: the reference drives this
from the PG's event loop; the TPU pipeline drives it from the host
dispatch loop between device batches. No locks needed.
"""

from __future__ import annotations

from collections import OrderedDict
from collections.abc import Callable

from .extents import ExtentSet
from .shard_map import ShardExtentMap
from .stripe import StripeInfo

LINE_SIZE = 32768  # bytes per shard per cache line (ECExtentCache.h)


class CacheOp:
    """One prepared RMW op: pinned lines + a promise of read data."""

    def __init__(
        self,
        oid: str,
        to_read: dict[int, ExtentSet],
        to_write: dict[int, ExtentSet],
        object_size: int,
        cb: Callable[["CacheOp"], None],
    ) -> None:
        self.oid = oid
        self.to_read = to_read
        self.to_write = to_write
        self.object_size = object_size
        self.cb = cb
        self.result: ShardExtentMap | None = None
        self.invoked = False
        self.done = False

    def lines(self) -> set[int]:
        out: set[int] = set()
        for es in list(self.to_read.values()) + list(self.to_write.values()):
            for start, end in es:
                out.update(range(start // LINE_SIZE, (end - 1) // LINE_SIZE + 1))
        return out


class ECExtentCache:
    """LRU of cache lines + FIFO op queues per object + one-at-a-time
    backend reads."""

    def __init__(
        self,
        sinfo: StripeInfo,
        backend_read: Callable[[str, dict[int, ExtentSet]], None],
        capacity_lines: int = 1024,
    ) -> None:
        self.sinfo = sinfo
        self.backend_read = backend_read
        self.capacity_lines = capacity_lines
        # (oid, line_no) -> pin count; OrderedDict doubles as LRU order.
        self._lines: OrderedDict[tuple[str, int], int] = OrderedDict()
        self._data: dict[str, ShardExtentMap] = {}
        self._present: dict[str, dict[int, ExtentSet]] = {}
        self._ops: dict[str, list[CacheOp]] = {}
        self._read_queue: list[CacheOp] = []
        self._active_read: CacheOp | None = None
        # counters (perf-counter hookup later)
        self.stat_hits = 0
        self.stat_misses = 0
        self.stat_evictions = 0

    # -- client API (prepare/execute/read_done/write_done) -------------
    def prepare(
        self,
        oid: str,
        to_read: dict[int, ExtentSet] | None,
        to_write: dict[int, ExtentSet],
        object_size: int,
        cb: Callable[[CacheOp], None],
    ) -> CacheOp:
        op = CacheOp(oid, to_read or {}, to_write, object_size, cb)
        for line in op.lines():
            key = (oid, line)
            self._lines[key] = self._lines.get(key, 0) + 1
            self._lines.move_to_end(key)
        return op

    def execute(self, ops: list[CacheOp]) -> None:
        for op in ops:
            self._ops.setdefault(op.oid, []).append(op)
            missing = self._missing(op)
            if missing:
                self.stat_misses += 1
                self._read_queue.append(op)
            else:
                self.stat_hits += 1
        self._maybe_issue_read()
        self._progress()

    def read_done(self, oid: str, smap: ShardExtentMap) -> None:
        """Backend read completed: publish data, continue the queue."""
        data = self._data.setdefault(oid, ShardExtentMap(self.sinfo))
        present = self._present.setdefault(oid, {})
        for shard in smap.shards():
            for start, end in smap.get_extent_set(shard):
                data.insert(shard, start, smap.get(shard, start, end - start))
                present.setdefault(shard, ExtentSet()).insert(start, end - start)
        if self._active_read is not None and self._active_read.oid == oid:
            self._active_read = None
        self._maybe_issue_read()
        self._progress()

    def write_done(self, op: CacheOp, written: ShardExtentMap) -> None:
        """Op complete: publish written buffers, unpin, evict as needed."""
        data = self._data.setdefault(op.oid, ShardExtentMap(self.sinfo))
        present = self._present.setdefault(op.oid, {})
        for shard in written.shards():
            for start, end in written.get_extent_set(shard):
                data.insert(shard, start, written.get(shard, start, end - start))
                present.setdefault(shard, ExtentSet()).insert(start, end - start)
        op.done = True
        for line in op.lines():
            key = (op.oid, line)
            if key in self._lines:
                self._lines[key] -= 1
        q = self._ops.get(op.oid, [])
        if op in q:
            q.remove(op)
        if not q:
            self._ops.pop(op.oid, None)
        self._evict()
        self._progress()
        # reads queued while this op held the FIFO (e.g. a truncate's
        # invalidation re-queuing a former cache hit) issue now
        self._maybe_issue_read()

    def on_change(self) -> None:
        """Drop everything not pinned (PG interval change analog)."""
        self._read_queue.clear()
        self._active_read = None
        self._evict(force_all=True)

    def invalidate_object(self, oid: str) -> None:
        """Drop one object's cached CONTENT (truncate invalidation):
        later ops re-read from the backend. Pins/line bookkeeping
        stay — they only gate eviction. Ops already queued as HITS
        must re-enter the read queue, or they would wait forever for
        extents nothing will produce; the read issues only after the
        invalidating op's write_done, so it sees post-truncate
        stores."""
        self._data.pop(oid, None)
        self._present.pop(oid, None)
        for op in self._ops.get(oid, []):
            if (
                not op.invoked
                and not op.done
                and op not in self._read_queue
                and self._missing(op)
            ):
                self._read_queue.append(op)

    # -- internals ------------------------------------------------------
    def _present_set(self, oid: str, shard: int) -> ExtentSet:
        return self._present.get(oid, {}).get(shard, ExtentSet())

    def _missing(self, op: CacheOp) -> dict[int, ExtentSet]:
        out: dict[int, ExtentSet] = {}
        for shard, es in op.to_read.items():
            miss = es.difference(self._present_set(op.oid, shard))
            if miss:
                out[shard] = miss
        return out

    def _maybe_issue_read(self) -> None:
        while self._active_read is None and self._read_queue:
            op = self._read_queue.pop(0)
            if op.done:
                continue
            missing = self._missing(op)
            if not missing:
                continue  # satisfied by an earlier op's read
            self._active_read = op
            self.backend_read(op.oid, missing)
            # backend_read may call read_done synchronously (memstore),
            # clearing _active_read — loop handles that.

    def _progress(self) -> None:
        """Fire ready callbacks strictly FIFO per object."""
        for oid, q in list(self._ops.items()):
            for op in list(q):
                if op.invoked:
                    # Invoked but still in the queue = its write hasn't
                    # landed (write_done removes completed ops). A later
                    # op must NOT proceed against pre-write cache state
                    # — that encodes stale data into parity. Serialize.
                    break
                if self._missing(op):
                    break  # never reorder: stop at first unready op
                op.result = self._snapshot(op)
                op.invoked = True
                op.cb(op)

    def _snapshot(self, op: CacheOp) -> ShardExtentMap:
        smap = ShardExtentMap(self.sinfo)
        data = self._data.get(op.oid)
        if data is None:
            return smap
        for shard, es in op.to_read.items():
            for start, end in es:
                smap.insert(shard, start, data.get(shard, start, end - start))
        return smap

    def _evict(self, force_all: bool = False) -> None:
        limit = 0 if force_all else self.capacity_lines
        unpinned = [k for k, pins in self._lines.items() if pins <= 0]
        excess = len(self._lines) - limit
        for key in unpinned:
            if excess <= 0:
                break
            oid, line = key
            del self._lines[key]
            excess -= 1
            self.stat_evictions += 1
            start = line * LINE_SIZE
            data = self._data.get(oid)
            if data is not None:
                for shard in list(data.shards()):
                    data.erase(shard, start, LINE_SIZE)
                    pres = self._present.get(oid, {}).get(shard)
                    if pres is not None:
                        pres.erase(start, LINE_SIZE)

    def lru_size(self) -> int:
        return len(self._lines)
