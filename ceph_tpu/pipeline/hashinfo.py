"""Per-shard cumulative CRC32C — the ``ECUtil::HashInfo`` analog.

Mirrors osd/ECUtil.h:731-780: one cumulative crc32c per shard, seeded
at -1 (0xFFFFFFFF), updated append-only as shards grow; persisted next
to the object and checked by deep scrub (ECBackend.cc:1829-1869).

Two append paths, bit-identical by construction:

- ``append``: raw bytes, routed through ``checksum.crc32c_stream``
  (host native below the device threshold, device-batched fold
  above) — the fallback tier.
- ``append_block_csums``: seeds the cumulative hashes from the fused
  encode+checksum kernel's ZERO-INIT per-block csums
  (ops/pallas_encode.py) via crc range concatenation — the bytes are
  hashed exactly once, on device, while they were resident for the
  encode matmul; the host never touches them again.
"""

from __future__ import annotations

import json

import numpy as np

from ceph_tpu.checksum import crc32c_chain, crc32c_stream

SEED = 0xFFFFFFFF


class HashInfo:
    def __init__(self, num_chunks: int) -> None:
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [SEED] * num_chunks

    def append(
        self,
        old_size: int,
        to_append: "dict[int, np.ndarray | bytes | bytearray | memoryview]",
    ) -> None:
        """Extend shard crcs with bytes written at ``old_size``.

        Values are raw shard bytes: bytes-like taken as-is, ndarrays
        must already be uint8 (no silent value casts — the crc is over
        stored bytes, so a lossy cast would hide corruption).

        The reference asserts appends are contiguous and equal-length
        across shards (HashInfo::append, ECUtil.cc); same contract here.
        """
        if old_size != self.total_chunk_size:
            raise ValueError(
                f"non-contiguous append: old_size={old_size}, "
                f"have={self.total_chunk_size}"
            )

        def as_bytes(b) -> bytes:
            if isinstance(b, (bytes, bytearray, memoryview)):
                return bytes(b)
            arr = np.asarray(b)
            if arr.dtype != np.uint8:
                raise TypeError(f"shard bytes must be uint8, got {arr.dtype}")
            return arr.tobytes()

        bufs = {shard: as_bytes(b) for shard, b in to_append.items()}
        sizes = {len(b) for b in bufs.values()}
        if len(sizes) > 1:
            raise ValueError(f"unequal append sizes {sizes}")
        for shard, data in bufs.items():
            self.cumulative_shard_hashes[shard] = crc32c_stream(
                data, self.cumulative_shard_hashes[shard]
            )
        if sizes:
            self.total_chunk_size += sizes.pop()

    def append_block_csums(
        self,
        old_size: int,
        to_append: "dict[int, np.ndarray]",
        block_bytes: int,
    ) -> None:
        """Extend shard crcs from kernel-produced ZERO-INIT per-block
        crc32c values (the fused encode+csum output) instead of raw
        bytes: cum' = A_block @ cum ⊕ crc_0(block), repeated — bit-
        identical to ``append`` over the same bytes, with no second
        pass over them. Same contiguity/equal-length contract."""
        if old_size != self.total_chunk_size:
            raise ValueError(
                f"non-contiguous append: old_size={old_size}, "
                f"have={self.total_chunk_size}"
            )
        blocks = {
            shard: np.asarray(v).reshape(-1)
            for shard, v in to_append.items()
        }
        sizes = {v.size for v in blocks.values()}
        if len(sizes) > 1:
            raise ValueError(f"unequal append sizes {sizes}")
        for shard, csums in blocks.items():
            self.cumulative_shard_hashes[shard] = crc32c_chain(
                self.cumulative_shard_hashes[shard], csums, block_bytes
            )
        if sizes:
            self.total_chunk_size += sizes.pop() * block_bytes

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def get_total_chunk_size(self) -> int:
        return self.total_chunk_size

    def has_chunk_hash(self) -> bool:
        return bool(self.cumulative_shard_hashes)

    def clear(self) -> None:
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [
            SEED for _ in self.cumulative_shard_hashes
        ]

    # -- persistence (the encode/decode-to-attr analog) ----------------
    def to_bytes(self) -> bytes:
        return json.dumps(
            {
                "total_chunk_size": self.total_chunk_size,
                "hashes": self.cumulative_shard_hashes,
            }
        ).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "HashInfo":
        obj = json.loads(raw.decode())
        hi = cls(len(obj["hashes"]))
        hi.total_chunk_size = obj["total_chunk_size"]
        hi.cumulative_shard_hashes = list(obj["hashes"])
        return hi

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, HashInfo)
            and self.total_chunk_size == other.total_chunk_size
            and self.cumulative_shard_hashes == other.cumulative_shard_hashes
        )

    def __repr__(self) -> str:
        return (
            f"HashInfo(size={self.total_chunk_size}, "
            f"crcs={[hex(h) for h in self.cumulative_shard_hashes]})"
        )
