"""CRUSH hierarchy: bucket tree, straw2 at every level, multi-step
rules — the src/crush analog (crush/crush.h:230 ``crush_bucket``,
mapper.c:826-2016 ``crush_do_rule``, builder.c map building,
CrushWrapper insert/move/reweight).

The flat straw2 map (placement.py) remains the degenerate case; this
module adds what it could not express:

- a **bucket tree** with arbitrary type levels (osd < host < rack <
  root by default), weights summing up the tree, built incrementally
  from device locations (``CrushWrapper::insert_item`` semantics);
- **multi-step rules**: ``take <bucket>``, ``choose firstn <n> type
  <t>``, ``chooseleaf firstn <n> type <t>``, ``emit`` — the working
  vector threads through the steps exactly like ``crush_do_rule``'s;
- **straw2 descent** with collision retries: at each level every
  child draws ``ln(u(key, child, r)) / weight`` and the max wins —
  weight-proportional, and reweighting moves only the items that now
  draw higher (CRUSH's minimal-movement property), now per level;
- **failure domains**: ``chooseleaf firstn 0 type rack`` spreads the
  k+m shards across racks, one leaf under each — a whole-rack loss
  degrades every PG by at most the shards it hosted there;
- **LRC locality**: a two-level rule (``choose`` locality buckets,
  ``chooseleaf`` within each) places each LRC layer group inside one
  locality bucket (ErasureCodeLrc.h crush-locality).

Hash discipline matches placement.py: the splitmix64-based
``stable_hash``, frozen forever by golden tests — determinism within
THIS framework is the contract, not rjenkins bit-compatibility.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from .placement import Device, _hash01

#: local retries per selection slot before giving up on distinctness
#: (choose_total_tries role, crush/mapper.c)
TOTAL_TRIES = 50

#: conventional type order, least to most aggregated; any type name
#: is allowed in buckets/rules — this only orders `osd tree` output
DEFAULT_TYPES = ("osd", "host", "rack", "row", "room", "root")


@dataclass
class Bucket:
    """One interior node (struct crush_bucket, straw2 only)."""

    name: str
    btype: str
    children: list[str | int] = field(default_factory=list)
    parent: str | None = None


def validate_rule(steps) -> tuple:
    """Normalize + validate rule steps; raises ValueError on anything
    run_rule would crash on (malformed control-plane input must fail
    at install time, not poison placement forever)."""
    norm = tuple(tuple(s) for s in steps)
    if not norm:
        raise ValueError("empty rule")
    if norm[0][:1] != ("take",):
        raise ValueError("rule must start with a take step")
    if norm[-1] != ("emit",):
        raise ValueError("rule must end with emit")
    if not any(
        s and s[0] in ("choose_firstn", "chooseleaf_firstn")
        for s in norm
    ):
        raise ValueError("rule selects nothing (no choose step)")
    for s in norm:
        if not s:
            raise ValueError("empty rule step")
        op = s[0]
        if op == "take":
            if len(s) != 2 or not isinstance(s[1], str):
                raise ValueError(f"take wants a bucket name: {s!r}")
        elif op in ("choose_firstn", "chooseleaf_firstn"):
            if (
                len(s) != 3
                or not isinstance(s[1], int)
                or s[1] < 0
                or not isinstance(s[2], str)
            ):
                raise ValueError(f"{op} wants (count, type): {s!r}")
        elif op == "emit":
            if len(s) != 1:
                raise ValueError(f"emit takes no arguments: {s!r}")
        else:
            raise ValueError(f"unknown rule step {op!r}")
    return norm


class CrushHierarchy:
    """Bucket tree + devices + rule execution (CrushWrapper role).

    ``strict`` controls conflicting-location handling: strict raises
    (the monitor validates operator input this way), non-strict keeps
    the first-seen parent (tolerant map decode — a historical map
    must never fail to load)."""

    def __init__(self, root: str = "default", strict: bool = True) -> None:
        self.root_name = root
        self.strict = strict
        self.buckets: dict[str, Bucket] = {
            root: Bucket(root, "root")
        }
        self.devices: dict[int, Device] = {}
        #: device id -> parent bucket name
        self._dev_parent: dict[int, str] = {}
        #: memoized subtree weights (cleared on any mutation)
        self._wcache: dict[str | int, float] = {}

    # -- building (builder.c / CrushWrapper::insert_item) ---------------
    def add_bucket(
        self, name: str, btype: str, parent: str | None = None
    ) -> Bucket:
        if name in self.buckets:
            b = self.buckets[name]
            if b.btype != btype:
                raise ValueError(
                    f"bucket {name!r} exists with type {b.btype!r}"
                )
            # re-link so a conflicting parent is detected (strict) or
            # ignored first-wins (tolerant) — not silently dropped
            self._link(b, parent or self.root_name)
            return b
        b = Bucket(name, btype)
        self.buckets[name] = b
        self._link(b, parent or self.root_name)
        return b

    def _link(self, bucket: Bucket, parent: str) -> None:
        if parent not in self.buckets:
            raise ValueError(f"no such parent bucket {parent!r}")
        if bucket.parent is not None and bucket.parent != parent:
            if self.strict:
                raise ValueError(
                    f"bucket {bucket.name!r} already under "
                    f"{bucket.parent!r}, conflicting location says "
                    f"{parent!r}"
                )
            return  # tolerant decode: first-seen parent wins
        bucket.parent = parent
        kids = self.buckets[parent].children
        if bucket.name not in kids:
            kids.append(bucket.name)

    def add_device(
        self, dev: Device, location: dict[str, str] | None = None
    ) -> None:
        """Insert a device at ``location`` (type -> bucket name, e.g.
        {"host": "h1", "rack": "r2"}), creating missing buckets chained
        in DEFAULT_TYPES order under the root — insert_item semantics."""
        self.devices[dev.id] = dev
        self._wcache.clear()
        loc = dict(location or {})
        # order the location levels least-aggregated first; unknown
        # types sort ALPHABETICALLY so the order is a function of the
        # location CONTENT — the monitor's strict validation pass and
        # the map rebuild must construct the identical tree no matter
        # what dict order each saw
        order = [t for t in DEFAULT_TYPES if t in loc] + sorted(
            t for t in loc if t not in DEFAULT_TYPES
        )
        if not order:
            self._dev_parent[dev.id] = self.root_name
            kids = self.buckets[self.root_name].children
            if dev.id not in kids:
                kids.append(dev.id)
            return
        # create/chain buckets from most-aggregated down
        parent = self.root_name
        for t in reversed(order):
            self.add_bucket(loc[t], t, parent)
            parent = loc[t]
        leaf_bucket = loc[order[0]]
        self._dev_parent[dev.id] = leaf_bucket
        kids = self.buckets[leaf_bucket].children
        if dev.id not in kids:
            kids.append(dev.id)

    def reweight(self, dev_id: int, weight: float) -> None:
        d = self.devices[dev_id]
        self.devices[dev_id] = Device(d.id, weight, d.zone)
        self._wcache.clear()

    # -- weights (summed up the tree, memoized per mutation epoch) ------
    def item_weight(self, item: str | int) -> float:
        w = self._wcache.get(item)
        if w is not None:
            return w
        if isinstance(item, int):
            d = self.devices.get(item)
            w = max(d.weight, 0.0) if d else 0.0
        else:
            b = self.buckets.get(item)
            w = (
                sum(self.item_weight(c) for c in b.children)
                if b is not None
                else 0.0
            )
        self._wcache[item] = w
        return w

    # -- straw2 ----------------------------------------------------------
    def _draw(self, key: tuple, item: str | int, trial: int) -> float:
        w = self.item_weight(item)
        if w <= 0:
            return -math.inf
        token = item if isinstance(item, int) else f"b:{item}"
        u = _hash01(*key, token, trial)
        return math.log(u) / w

    def _choose_child(
        self, key: tuple, bucket: Bucket, trial: int
    ) -> str | int | None:
        best, best_draw = None, -math.inf
        for c in bucket.children:
            d = self._draw(key, c, trial)
            if d > best_draw:
                best, best_draw = c, d
        return best if best_draw > -math.inf else None

    def _descend(
        self,
        key: tuple,
        start: str | int,
        target_type: str,
        trial: int,
    ) -> str | int | None:
        """Walk from ``start`` toward an item of ``target_type``
        (device when target_type == "osd"), one straw2 draw per
        level (crush_choose_firstn's recursion)."""
        cur: str | int = start
        for _depth in range(16):  # tree depth bound
            if isinstance(cur, int):
                return cur if target_type == "osd" else None
            if cur in self.buckets and self.buckets[cur].btype == target_type:
                return cur
            b = self.buckets.get(cur)
            if b is None:
                return None
            nxt = self._choose_child(key, b, trial)
            if nxt is None:
                return None
            cur = nxt
        return None

    def _choose_n(
        self,
        key: tuple,
        start: str | int,
        n: int,
        target_type: str,
        chooseleaf: bool,
        taken: set,
    ) -> list:
        """firstn selection of n distinct items of target_type below
        start; with chooseleaf, one distinct DEVICE under each chosen
        bucket is returned instead (chooseleaf_firstn)."""
        out: list = []
        chosen: set = set()  # intermediate-bucket distinctness
        for slot in range(n):
            pick = None
            for attempt in range(TOTAL_TRIES):
                trial = slot + n * attempt
                cand = self._descend(key, start, target_type, trial)
                if cand is None or cand in chosen:
                    continue
                if chooseleaf:
                    leaf = None
                    for lattempt in range(TOTAL_TRIES):
                        leaf_cand = self._descend(
                            (*key, "leaf"), cand, "osd",
                            slot + n * lattempt,
                        )
                        if leaf_cand is not None and leaf_cand not in taken:
                            leaf = leaf_cand
                            break
                    if leaf is None:
                        continue  # bucket has no usable leaf: re-draw
                    pick = leaf
                else:
                    if cand in taken:
                        continue
                    pick = cand
                chosen.add(cand)
                taken.add(pick)
                out.append(pick)
                break
            if pick is None:
                break  # undersized: ran out of distinct candidates
        return out

    # -- rules (crush_do_rule) -------------------------------------------
    def run_rule(
        self, rule: tuple, key: tuple | int, n: int
    ) -> list[int]:
        """Execute rule steps for selection key ``key`` wanting ``n``
        items. Steps (tuples):

            ("take", bucket_name)
            ("choose_firstn", count, type)      # count 0 => n
            ("chooseleaf_firstn", count, type)  # count 0 => n
            ("emit",)

        Returns device ids (in draw order — position is EC shard).
        A ``choose_firstn`` that selects buckets threads them as the
        working vector into the next step, splitting the remaining
        want across them (crush_do_rule's wv recursion)."""
        if isinstance(key, int):
            key = (key,)
        working: list[str | int] = []
        result: list[int] = []
        taken: set = set()
        for step in rule:
            op = step[0]
            if op == "take":
                working = [step[1]]
            elif op in ("choose_firstn", "chooseleaf_firstn"):
                count = step[1] or n
                ttype = step[2]
                leaf = op == "chooseleaf_firstn"
                nxt: list[str | int] = []
                for w in working:
                    nxt.extend(
                        self._choose_n(
                            tuple(key) + ((f"w:{w}",) if len(working) > 1 else ()),
                            w, count, ttype,
                            chooseleaf=leaf, taken=taken,
                        )
                    )
                working = nxt
            elif op == "emit":
                result.extend(
                    w for w in working if isinstance(w, int)
                )
                working = []
            else:
                raise ValueError(f"unknown rule step {op!r}")
        return result[:n] if n else result


def ec_rule(
    failure_domain: str = "host", root: str = "default"
) -> tuple:
    """The standard EC pool rule: spread k+m leaves across distinct
    failure-domain buckets (ErasureCode::create_rule,
    erasure-code/ErasureCode.cc:70)."""
    if failure_domain in ("", "osd"):
        return (("take", root), ("choose_firstn", 0, "osd"), ("emit",))
    return (
        ("take", root),
        ("chooseleaf_firstn", 0, failure_domain),
        ("emit",),
    )


def lrc_rule(
    groups: int,
    per_group: int,
    locality: str,
    failure_domain: str = "host",
    root: str = "default",
) -> tuple:
    """LRC crush-locality rule: pick ``groups`` locality buckets, then
    ``per_group`` leaves (across distinct failure domains) inside
    each — every layer group's chunks stay local to one bucket, so a
    local repair never crosses it (ErasureCodeLrc.h crush-locality)."""
    if failure_domain in ("", "osd") or failure_domain == locality:
        inner: tuple = ("choose_firstn", per_group, "osd")
    else:
        inner = ("chooseleaf_firstn", per_group, failure_domain)
    return (
        ("take", root),
        ("choose_firstn", groups, locality),
        inner,
        ("emit",),
    )
