"""Round-19: multi-tenant QoS sweep — the prepared tunnel run for
ISSUE 19's acceptance numbers.

Client ops now carry a tenant identity end-to-end (client -> objecter
-> OSDOp wire -> per-tenant dmClock class on every OSD), pool QoS
specs ride the map, costs are byte-proportional, and the
``osd_mclock_profile`` slosh knob re-splits capacity between clients
and recovery. This script measures what the plane buys:

- the noisy-neighbor ladder: tenant A's p99 vs tenant-B flood
  intensity (queue-depth rungs), with and without concurrent
  recovery, QoS armed — the bound must hold flat-ish while the
  ``osd_op_qos=false`` escape hatch at the top rung blows past it;
- the slosh curve: time-to-recovered vs tenant-A p99 across
  high_client / balanced / high_recovery — the knob must trade them
  monotonically (>=3 settings, the acceptance shape);
- per-tenant p99 rows in BOTH clocks (host and device-clock mode) at
  the contended point — the tunnel row BASELINE.md wants.

Run on the v5e tunnel:

    python experiments/exp_r19_qos.py          # full sweep
    python experiments/exp_r19_qos.py --quick  # CI-sized

The CPU fallback runs the same legs at toy sizes (correctness smoke;
absolute latencies mean nothing off-TPU)."""

import json
import sys
import time

sys.path.insert(0, ".")

QUICK = "--quick" in sys.argv


def _leg(tag, out, *, total_ops, qd, objects, flood_qd=0,
         flood_mult=2, faults=False, qos_on=True, profile="balanced",
         device_clock=False, object_size=64 * 1024, seed=0xEC19):
    """One multi-tenant run: tenant A's modest read-heavy mix with a
    reservation+weight spec, optionally tenant B's write flood at
    ``flood_qd`` on top, optionally a mid-run most-primary
    kill/revive."""
    from ceph_tpu.loadgen import LoadCluster, WorkloadSpec, run_spec
    from ceph_tpu.loadgen.faults import FaultEvent, FaultSchedule
    from ceph_tpu.utils import config

    tenants: dict = {
        "tenantA": {
            "mix": {"seq_write": 1, "read": 3, "rmw_overwrite": 1},
            "object_size": object_size,
            "queue_depth": max(qd // 4, 2),
            "total_ops": total_ops,
            "qos": {"res_ops": 64.0, "res_bytes": 8 << 20,
                    "weight": 4.0},
        },
    }
    if flood_qd:
        tenants["tenantB"] = {
            "mix": {"seq_write": 3, "rand_write": 2},
            "object_size": object_size * 4,
            "queue_depth": flood_qd,
            "total_ops": total_ops * flood_mult,
            "qos": {"weight": 1.0},
        }
    with config.override(osd_op_qos=qos_on,
                         osd_mclock_profile=profile):
        cluster = LoadCluster(
            n_osds=6, k=4, m=2, pg_num=8, chunk_size=16384,
        )
        try:
            spec = WorkloadSpec(
                mix={"seq_write": 1, "read": 1},
                object_size=object_size, max_objects=objects,
                queue_depth=qd, total_ops=total_ops,
                warmup_ops=max(total_ops // 10, 8),
                popularity="zipfian", device_clock=device_clock,
                seed=seed, tenants=tenants,
            )
            schedule = None
            if faults:
                schedule = FaultSchedule(
                    [FaultEvent(at_op=total_ops // 3, action="kill"),
                     FaultEvent(at_op=(2 * total_ops) // 3,
                                action="revive")],
                )
            t0 = time.monotonic()
            report = run_spec(cluster, spec, schedule)
        finally:
            cluster.shutdown()
    a = report["tenants"]["tenantA"]
    row = {
        "tenantA_p99_ms": a.get("lat_p99_ms"),
        "tenantA_iops": round(a["ops"] / a["duration_s"], 2)
        if a.get("duration_s") else None,
        "errors": report["errors"],
        "verify_failures": report["verify_failures"],
        "wall_s": round(time.monotonic() - t0, 2),
    }
    if device_clock:
        row["tenantA_p99_ms_device"] = a.get("lat_p99_ms_device")
        b = report["tenants"].get("tenantB", {})
        row["tenantB_p99_ms_device"] = b.get("lat_p99_ms_device")
    if faults and "fault" in report:
        row["time_to_recovered_s"] = report["fault"].get(
            "time_to_recovered_s")
    out[tag] = row
    print(f"  {tag}: {row}", flush=True)
    return report


def main() -> None:
    from ceph_tpu.utils import honor_platform_env

    honor_platform_env()
    import jax

    ops = 48 if QUICK else 720
    objects = 24 if QUICK else 512
    qd = 8 if QUICK else 32
    osize = 16 * 1024 if QUICK else 256 * 1024
    out: dict = {"platform": jax.devices()[0].platform,
                 "ops": ops, "objects": objects, "qd": qd}

    print("== noisy-neighbor ladder: flood qd x recovery ==",
          flush=True)
    rungs = (0, qd // 2, qd) if QUICK else (0, qd // 2, qd, qd * 2)
    for flood_qd in rungs:
        for faults in (False, True):
            tag = (f"flood{flood_qd}" + ("_recovery" if faults else ""))
            _leg(tag, out, total_ops=ops, qd=qd, objects=objects,
                 flood_qd=flood_qd, faults=faults,
                 object_size=osize, seed=0xEC19)
    # the escape hatch at the top rung: same storm, flat class
    _leg("hatch_noqos", out, total_ops=ops, qd=qd, objects=objects,
         flood_qd=rungs[-1], faults=True, qos_on=False,
         object_size=osize, seed=0xEC19)
    solo = out["flood0"]["tenantA_p99_ms"]
    top = out[f"flood{rungs[-1]}_recovery"]["tenantA_p99_ms"]
    hatch = out["hatch_noqos"]["tenantA_p99_ms"]
    if solo:
        out["noisy_neighbor_frac"] = round(top / solo, 3)
        out["escape_hatch_frac"] = round(hatch / solo, 3)
        out["accept_qos_beats_hatch"] = bool(top < hatch)

    print("== slosh curve: >=3 knob settings ==", flush=True)
    curve = {}
    for prof in ("high_client", "balanced", "high_recovery"):
        rep = _leg(f"slosh_{prof}", out, total_ops=ops, qd=qd,
                   objects=objects, flood_qd=qd // 2, faults=True,
                   profile=prof, object_size=osize, seed=0x5119)
        curve[prof] = out[f"slosh_{prof}"].get("time_to_recovered_s")
    if all(v is not None for v in curve.values()):
        out["accept_slosh_monotone"] = bool(
            curve["high_recovery"] <= curve["balanced"]
            <= curve["high_client"]
        )

    print("== per-tenant p99, device clock (the tunnel row) ==",
          flush=True)
    _leg("contended_device_clock", out, total_ops=ops, qd=qd,
         objects=objects, flood_qd=qd // 2, device_clock=True,
         object_size=osize, seed=0xEC19)

    print(json.dumps(out, indent=2, sort_keys=True))


if __name__ == "__main__":
    main()
