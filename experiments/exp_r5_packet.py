"""Round-5 experiment: where do the packet codes lose 200+ GB/s?

Bench r4: liberation 82.6, blaum_roth 35.1, liber8tion 48.6 GB/s via
the codec path, while the bare kernel at comparable contraction width
(isa k=21, c=21 -> F=32) ran 369. Factors to separate:

  A. shape smallness: the family bench uses 32 stripes x ~200 KiB
     chunks (25-29 MB/iter) vs isa_k21m4's 344 MB/iter
  B. the codec-path packetize/stack/restack XLA ops around the kernel
  C. the packet matrix itself (r = m*w acc rows vs m)

Run on the real chip: python experiments/exp_r5_packet.py
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from ceph_tpu.gf import gf_matrix_to_bitmatrix, isa_rs_matrix
from ceph_tpu.ops import pallas_encode as pe


def loop_gbps(apply, data, n1=5, n2=25, reps=3):
    batch, k, n = data.shape

    @jax.jit
    def loop(d0, iters):
        def body(i, carry):
            d, acc = carry
            patch = (
                jax.lax.dynamic_slice(d, (0, 0, 0), (1, 1, 128))
                ^ jnp.uint8(i + 1)
            )
            d = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
            out = apply(d)
            fold = jax.lax.dynamic_slice(out, (0, 0, 0), (1, 1, 128))[0, 0, 0]
            return d, acc ^ fold

        _, acc = jax.lax.fori_loop(0, iters, body, (d0, jnp.uint8(0)))
        return acc

    def timed(iters):
        t0 = time.perf_counter()
        np.asarray(loop(data, iters))
        return time.perf_counter() - t0

    for t in (n1, n2):
        timed(t)
    diffs = []
    for _ in range(reps):
        d = (timed(n2) - timed(n1)) / (n2 - n1)
        if d > 0:
            diffs.append(d)
    dt = float(np.median(diffs))
    return batch * k * n / dt / 1e9


def main():
    rng = np.random.default_rng(11)
    from ceph_tpu.codecs import registry

    codec = registry.factory(
        "jerasure", {"technique": "liberation", "k": "4", "m": "2", "w": "7"}
    )
    w = codec.w
    kw, mw = 4 * w, 2 * w
    lib_bmat = np.asarray(codec._encode_bmat_np)  # [mw*8, kw*8]

    # C: bare kernel, packet matrix, pre-packetized input (no codec ops)
    for stripes, lane in ((32, 32768), (128, 32768), (32, 65536), (64, 65536)):
        data = jnp.asarray(
            rng.integers(0, 256, (stripes, kw, lane), np.uint8)
        )
        g = loop_gbps(
            lambda d: pe.gf_encode_bitplane_pallas(lib_bmat, d), data
        )
        print(f"bare liberation packet-matrix [{stripes},{kw},{lane}]: {g:.1f} GB/s", flush=True)

    # B: synthetic byte code with the same c=28 contraction, r=2 vs r=14
    gm = isa_rs_matrix(28, 2)
    bm = gf_matrix_to_bitmatrix(np.asarray(gm)[28:, :])
    data = jnp.asarray(rng.integers(0, 256, (32, 28, 32768), np.uint8))
    g = loop_gbps(lambda d: pe.gf_encode_bitplane_pallas(bm, d), data)
    print(f"bare byte c=28 r=2 [32,28,32768]: {g:.1f} GB/s", flush=True)

    gm = isa_rs_matrix(28, 14)
    bm = gf_matrix_to_bitmatrix(np.asarray(gm)[28:, :])
    g = loop_gbps(lambda d: pe.gf_encode_bitplane_pallas(bm, d), data)
    print(f"bare byte c=28 r=14 [32,28,32768]: {g:.1f} GB/s", flush=True)

    # A: codec path exactly as bench _measure_code_families runs it
    chunk = 7 * 32768
    for stripes in (32, 128):
        full = jnp.asarray(
            rng.integers(0, 256, (stripes, 4, chunk), np.uint8)
        )

        def apply(d):
            parity = codec.encode_chunks({i: d[:, i, :] for i in range(4)})
            return jnp.stack([parity[j] for j in sorted(parity)], axis=1)

        g = loop_gbps(apply, full)
        print(f"codec path liberation [{stripes},4,{chunk}]: {g:.1f} GB/s", flush=True)


if __name__ == "__main__":
    main()
