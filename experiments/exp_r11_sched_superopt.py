"""Round-11: XOR-schedule superoptimization sweep (arxiv 2108.02692).

The schedule builder became an optimizer: greedy pairwise CSE factors
shared XOR subexpressions across parity rows into VMEM-scratch
intermediates, the DAG is linearized for operand locality, and the
route gate moved to post-CSE op count — which admits inverted decode
matrices (~50% ones, raw ratio 7-8) and LRC xor-local-parity repair
to the schedule route the raw density gate locked out. This script is
the tunnel evidence run behind the round-11 BASELINE rows. Run on the
v5e tunnel:

    python experiments/exp_r11_sched_superopt.py

Legs (each printed as its own table):

1. op-count scorecard — ones / selection XORs / post-CSE XORs /
   intermediates / scratch-slot peak, per family encode matrix AND
   per 2-lost inverted decode matrix (host-side; matches the tier-1
   golden pins).
2. encode A/B — family encode GB/s with ec_sched_opt on vs off
   (same geometry as bench.py's code-families phase). Target: opt >=
   unopt everywhere, and a new dispatch ceiling > 537 GB/s.
3. inverted-decode A/B — 2-lost-chunk decode GB/s through the
   schedule route (optimizer on; the matrix CSE-compresses under the
   gate) vs the MXU engine (ec_use_sched off). The round-11 claim:
   decode/repair shapes now ride the fixed engine.
4. LRC local repair — single-lost-chunk repair GB/s,
   local_parity=xor (schedule route) vs the default rs layout (MXU
   route), survivor-bytes-in basis — the `lrc_*_gbps >= 200` check.

Off-TPU it degrades to an interpret-mode bit-equality smoke on tiny
shapes (timings mean nothing there).
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np
import jax
import jax.numpy as jnp

from ceph_tpu.codecs.registry import registry
from ceph_tpu.ops import xor_schedule as xs
from ceph_tpu.utils import config

FAMILIES = [
    ("liberation", {"technique": "liberation", "k": "4", "m": "2",
                    "w": "7"}, 7 * 16384, 160),
    ("blaum_roth", {"technique": "blaum_roth", "k": "4", "m": "2",
                    "w": "6"}, 6 * 16384, 192),
    ("liber8tion", {"technique": "liber8tion", "k": "4", "m": "2",
                    "w": "8"}, 8 * 16384, 128),
]


def timed(fn, *args):
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def loop_stats(loop, data, target=0.45, reps=4):
    base = min(timed(loop, data, 1) for _ in range(2))
    n2 = 60
    while n2 < 40000:
        if timed(loop, data, n2) - base >= target:
            break
        n2 *= 2
    n1 = max(1, n2 // 10)
    t1 = min(timed(loop, data, n1) for _ in range(reps))
    t2 = min(timed(loop, data, n2) for _ in range(reps))
    return (t2 - t1) / (n2 - n1)


def dev_rand(shape, seed):
    key = jax.random.PRNGKey(seed)
    return jax.random.randint(key, shape, 0, 256, jnp.int32).astype(
        jnp.uint8
    )


def shard_loop(apply_shards, nshards, chunk, stripes, seed):
    """Feedback loop over a tuple of [stripes, chunk] shard arrays;
    apply_shards(dict) -> list of output arrays."""
    sz = stripes * chunk
    flat = dev_rand((nshards * sz,), seed)
    arrs = tuple(
        flat[i * sz : (i + 1) * sz].reshape(stripes, chunk)
        for i in range(nshards)
    )

    @jax.jit
    def loop(arrs, iters):
        def body(i, carry):
            arrs, acc = carry
            outs = apply_shards(arrs)
            fold = jax.lax.dynamic_slice(outs[0], (0, 0), (1, 128))
            scalar = fold[0, 0]
            for o in outs[1:]:
                scalar = scalar ^ o[0, 0]
            first = jax.lax.dynamic_update_slice(
                arrs[0], fold ^ jnp.uint8(i + 1), (0, 0)
            )
            return (first,) + arrs[1:], acc ^ scalar

        _, acc = jax.lax.fori_loop(0, iters, body, (arrs, jnp.uint8(0)))
        return acc

    return loop, arrs


def leg1_op_counts():
    print("== leg 1: op-count scorecard (host-side)")
    print(f"{'matrix':34s} {'ones':>5s} {'raw':>5s} {'cse':>5s} "
          f"{'tmps':>5s} {'slots':>5s} {'save':>6s}")
    for fam, profile, _c, _s in FAMILIES:
        codec = registry.factory("jerasure", dict(profile))
        st = xs.cse_stats(codec.coding_bitmatrix)
        print(f"{fam + ' encode':34s} {st['ones']:5d} "
              f"{st['raw_xors']:5d} {st['opt_xors']:5d} "
              f"{st['temps']:5d} {st['scratch_slots']:5d} "
              f"{st['saving_frac']:6.1%}")
        dec = codec._build_decode_bitmatrix([2, 3, 4, 5], [0, 1])
        st = xs.cse_stats(dec)
        ratio_raw = (st["ones"] + dec.shape[0]) / dec.shape[1]
        ratio_opt = (st["opt_xors"] + dec.shape[0]) / dec.shape[1]
        print(f"{fam + ' decode lose(0,1)':34s} {st['ones']:5d} "
              f"{st['raw_xors']:5d} {st['opt_xors']:5d} "
              f"{st['temps']:5d} {st['scratch_slots']:5d} "
              f"{st['saving_frac']:6.1%}  "
              f"(gate ratio {ratio_raw:.2f} -> {ratio_opt:.2f})")


def leg2_encode_ab():
    print("== leg 2: encode A/B (ec_sched_opt on vs off), GB/s data-in")
    ceiling = 0.0
    for fam, profile, chunk, stripes in FAMILIES:
        codec = registry.factory("jerasure", dict(profile))
        k = codec.k
        rates = {}
        for opt in (True, False):
            with config.override(ec_sched_opt=opt):
                def apply(arrs, codec=codec, k=k):
                    p = codec.encode_chunks(
                        {i: arrs[i] for i in range(k)}
                    )
                    return [p[j] for j in sorted(p)]

                loop, arrs = shard_loop(apply, k, chunk, stripes, 31)
                per = loop_stats(loop, arrs)
            rates[opt] = stripes * k * chunk / per / 1e9
        ceiling = max(ceiling, rates[True])
        print(f"  {fam}: opt {rates[True]:7.1f}  unopt "
              f"{rates[False]:7.1f}  ratio {rates[True]/rates[False]:.3f}")
    print(f"  dispatch ceiling (opt): {ceiling:.1f} GB/s "
          f"(round-11 target > 537)")


def leg3_decode_ab():
    print("== leg 3: 2-lost inverted decode, schedule route vs MXU")
    for fam, profile, chunk, stripes in FAMILIES:
        codec = registry.factory("jerasure", dict(profile))
        k = codec.k
        keys = [2, 3, 4, 5]  # survivors: 2 data + 2 parity

        def apply(arrs, codec=codec, keys=keys):
            out = codec.decode_chunks(
                {0, 1}, dict(zip(keys, arrs))
            )
            return [out[0], out[1]]

        rates = {}
        for sched_on in (True, False):
            with config.override(ec_use_sched=sched_on):
                loop, arrs = shard_loop(
                    apply, len(keys), chunk, stripes, 37
                )
                per = loop_stats(loop, arrs)
            rates[sched_on] = len(keys) * stripes * chunk / per / 1e9
        print(f"  {fam}: sched {rates[True]:7.1f}  mxu "
              f"{rates[False]:7.1f}  ratio "
              f"{rates[True]/rates[False]:.3f}")


def leg4_lrc_local():
    print("== leg 4: LRC local repair (survivor-bytes-in GB/s)")
    chunk, stripes = 65536, 256
    for name, extra in (("xor", {"local_parity": "xor"}), ("rs", {})):
        codec = registry.factory(
            "lrc", {"k": "4", "m": "2", "l": "3", **extra}
        )
        plan = codec.minimum_to_decode(
            {0}, set(range(codec.k + codec.m)) - {0}
        )
        keys = sorted(plan)

        def apply(arrs, codec=codec, keys=keys):
            return [
                codec.decode_chunks({0}, dict(zip(keys, arrs)))[0]
            ]

        loop, arrs = shard_loop(apply, len(keys), chunk, stripes, 41)
        per = loop_stats(loop, arrs)
        gbps = len(keys) * stripes * chunk / per / 1e9
        print(f"  local_parity={name}: {gbps:7.1f} GB/s "
              f"({len(keys)} survivors read; target >= 200)")


def smoke_off_tpu():
    print("off-TPU: interpret-mode bit-equality smoke")
    import functools

    xs.on_tpu = lambda: True
    orig = xs.xor_schedule_apply_shards
    xs.xor_schedule_apply_shards = functools.partial(
        orig, interpret=True
    )
    rng = np.random.default_rng(5)
    codec = registry.factory(
        "jerasure",
        {"technique": "liberation", "k": "4", "m": "2", "w": "7"},
    )
    n = 7 * 2048
    data = {
        i: jnp.asarray(rng.integers(0, 256, (8, n), np.uint8))
        for i in range(4)
    }
    parity = codec.encode_chunks(dict(data))
    with config.override(ec_sched_opt=False):
        ref = codec.encode_chunks(dict(data))
    ok = all(
        (np.asarray(parity[i]) == np.asarray(ref[i])).all()
        for i in parity
    )
    print("  liberation encode opt == unopt:", ok)
    chunks = {**data, **parity}
    del chunks[0], chunks[1]
    out = codec.decode_chunks({0, 1}, chunks)
    ok = (np.asarray(out[0]) == np.asarray(data[0])).all() and (
        np.asarray(out[1]) == np.asarray(data[1])
    ).all()
    print("  liberation 2-lost decode via schedule route:", ok)


def main():
    leg1_op_counts()
    if not xs.on_tpu():
        smoke_off_tpu()
        return
    leg2_encode_ab()
    leg3_decode_ab()
    leg4_lrc_local()


if __name__ == "__main__":
    main()
