"""Round-5: shards-form MXU kernel, take 2.

Blocks must be sublane-aligned (last-two block dims divisible by
(8, 128) — the take-1 (2, tile) block refused to lower), so the block
carries SB=8 stripes of every shard and the kernel loops over
SB/s groups of s stripes, each group one stationary matmul with
contraction 8*(s*c).

The stationary matrix is SHARD-MAJOR (col = b*F + i*s + si) so each
group's flat input is a concat of contiguous [s, T] slices of the
shard refs — no per-row sublane gathers.
"""

import functools
import sys

sys.path.insert(0, ".")

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ceph_tpu.gf import gf_matrix_to_bitmatrix, vandermonde_rs_matrix
from ceph_tpu.ops import pallas_encode as pe
from ceph_tpu.ops.pallas_encode import unpack_bitplanes
from experiments.exp_r5_multiop_byte import (
    build_loop_shards,
    build_loop_stacked,
    dev_rand,
    loop_stats,
)

SB = 8


def _v4_matrix(bitmatrix, c, r, s, pad):
    """Stationary matrix, shard-major columns.

    acc row  = h*(4*s*r) + si*(4*r) + j*4 + b2   (same as v3)
    bits col = b*F + i*s + si, F = s*c + pad
    """
    f = s * c + pad
    mat = np.zeros((8 * s * r, 8 * f), np.int8)
    for h in range(2):
        for si in range(s):
            for j in range(r):
                for b2 in range(4):
                    bp = h * 4 + b2
                    row = h * (4 * s * r) + si * (4 * r) + j * 4 + b2
                    for b in range(8):
                        for i in range(c):
                            mat[row, b * f + i * s + si] = bitmatrix[
                                j * 8 + bp, i * 8 + b
                            ]
    return mat


def make_shards_kernel(bitmatrix, k, m, s, tile):
    from jax.experimental.pallas import tpu as pltpu

    c = k
    pad = (-s * c) % 4
    groups = SB // s
    big = _v4_matrix(np.asarray(bitmatrix, np.uint8), c, m, s, pad)

    def kernel(bmat_ref, *refs):
        ins, outs = refs[:k], refs[k:]
        t = ins[0].shape[1]
        for g in range(groups):
            parts = [ins[i][g * s : (g + 1) * s, :] for i in range(c)]
            flat = jnp.concatenate(parts, axis=0)  # [s*c, T] (i, si)
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad, t), jnp.uint8)], axis=0
                )
            bits = unpack_bitplanes(flat, False)
            acc = jax.lax.dot_general(
                bmat_ref[:], bits, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            acc8 = acc.astype(jnp.int8)
            p32 = pltpu.bitcast(acc8, jnp.int32)
            masked = p32 & jnp.int32(0x01010101)
            nib = (
                masked | (masked >> jnp.int32(7))
                | (masked >> jnp.int32(14)) | (masked >> jnp.int32(21))
            ) & jnp.int32(0xF)
            sr = s * m
            out32 = nib[0:sr] | (nib[sr : 2 * sr] << jnp.int32(4))
            out8 = out32.astype(jnp.uint8).reshape(s, m, t)
            for j in range(m):
                outs[j][g * s : (g + 1) * s, :] = out8[:, j, :]

    @jax.jit
    def apply(*shards):
        b, n = shards[0].shape
        return pl.pallas_call(
            kernel,
            grid=(b // SB, n // tile),
            in_specs=[pl.BlockSpec(big.shape, lambda i, c2: (0, 0))]
            + [
                pl.BlockSpec((SB, tile), lambda i, c2: (i, c2))
                for _ in range(k)
            ],
            out_specs=[
                pl.BlockSpec((SB, tile), lambda i, c2: (i, c2))
                for _ in range(m)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, n), jnp.uint8)
                for _ in range(m)
            ],
        )(big, *shards)

    return apply


def sweep(k, m, batch, chunk, tiles, ss):
    g = vandermonde_rs_matrix(k, m)
    bmat = gf_matrix_to_bitmatrix(g[k:, :])
    nbytes = batch * k * chunk

    data = dev_rand((batch, k, chunk), 0)
    loop = build_loop_stacked(lambda d: pe.gf_encode_bitplane_pallas(bmat, d))
    per = loop_stats(loop, data)
    print(f"  stacked v3 auto: {nbytes/per/1e9:.1f} GB/s", flush=True)

    small = tuple(dev_rand((8, 8192), 10 + i) for i in range(k))
    stacked_small = jnp.stack(small, axis=1)
    want = pe.gf_encode_bitplane_pallas(bmat, stacked_small)
    shards = tuple(dev_rand((batch, chunk), 20 + i) for i in range(k))
    for s in ss:
        try:
            ap = make_shards_kernel(bmat, k, m, s, 8192)
            outs = ap(*small)
            ok = all(
                np.array_equal(np.asarray(outs[j]), np.asarray(want[:, j, :]))
                for j in range(m)
            )
        except Exception as e:
            print(f"  shards s={s}: build fail {type(e).__name__} "
                  f"{str(e)[:90]}", flush=True)
            continue
        for tile in tiles:
            if chunk % tile:
                continue
            try:
                ap = make_shards_kernel(bmat, k, m, s, tile)
                loop = build_loop_shards(ap)
                per = loop_stats(loop, shards)
                print(
                    f"  shards s={s} F={s*k} tile={tile}: "
                    f"{nbytes/per/1e9:.1f} GB/s ok={ok}",
                    flush=True,
                )
            except Exception as e:
                print(f"  shards s={s} tile={tile}: {type(e).__name__} "
                      f"{str(e)[:90]}", flush=True)


def main():
    print("flagship (8,4) batch=8 chunk=1M:", flush=True)
    sweep(8, 4, 8, 1 << 20, (16384, 32768, 65536), (2, 4, 8))
    print("shec-geom (4,3) batch=256 chunk=64K:", flush=True)
    sweep(4, 3, 256, 65536, (16384, 32768, 65536), (2, 4, 8))
    print("lrc-local (2,1) batch=256 chunk=64K:", flush=True)
    sweep(2, 1, 256, 65536, (32768, 65536), (2, 4, 8))


if __name__ == "__main__":
    main()
