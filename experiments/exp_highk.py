"""High-k kernel packing experiments (round 4, not part of the package).

The shipping v3 kernel only uses the 128-contraction two-stripe layout
when 2*c <= 16, so k=10..32 pays single-stripe + pad (VERDICT r3 weak
#2: cauchy_k10m4 at 96 GB/s vs 305 flagship). Variants measured here:

  cur      — shipping kernel as-is
  padF     — pad F up to a power-of-two-friendly width (shift divisor
             f//4 becomes a power of two; the iota//3 in the unpack is
             a non-pow2 integer division per element)
  cshift   — replace the iota//q shift computation with a precomputed
             constant vector (kills the division for every F)
  sN-F     — stripes-per-block sweep: s chosen so F = s*c + pad hits
             16/32/48/64 contraction bytes

Usage: PYTHONPATH=/root/repo python exp_highk.py [k m] [variants...]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ceph_tpu.gf import gf_matrix_to_bitmatrix
from ceph_tpu.gf.matrices import cauchy_good_matrix
from ceph_tpu.ops import pallas_encode as pe

CHUNK = 1 << 20
BATCH = 8
N1, N2 = 10, 60
REPS = 5


def _timed(fn, *args) -> float:
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def _gbps(apply, data, k) -> float:
    batch, _, n = data.shape

    @jax.jit
    def loop(d0, iters):
        def body(i, carry):
            d, acc = carry
            patch = (
                jax.lax.dynamic_slice(d, (0, 0, 0), (1, 1, 128))
                ^ jnp.uint8(i + 1)
            )
            d = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
            out = apply(d)
            fold = jax.lax.dynamic_slice(
                out, (0, 0, 0), (1, 1, 128)
            )
            return d, acc ^ fold

        _, acc = jax.lax.fori_loop(
            0, iters, body, (d0, jnp.zeros((1, 1, 128), jnp.uint8))
        )
        return acc[0, 0, 0]

    diffs = []
    for _ in range(REPS):
        d = (_timed(loop, data, N2) - _timed(loop, data, N1)) / (N2 - N1)
        if d > 0:
            diffs.append(d)
    dt = float(np.median(diffs)) if diffs else float("nan")
    return batch * k * n / dt / 1e9


# ---------------------------------------------------------- variant kernel
# Parameterized copy of the v3 kernel with (a) arbitrary F target and
# (b) optional constant shift vector.
def _var_matrix(bitmatrix: np.ndarray, c: int, r: int, s: int, pad: int):
    return pe._v3_matrix(bitmatrix, c, r, s, pad)


def _make_kernel(c, r, s, pad, const_shift):
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bmat_ref, data_ref, out_ref):
        d = data_ref[:]
        t = d.shape[2]
        flat = d.reshape(s * c, t)
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad, t), jnp.uint8)], axis=0
            )
        f = s * c + pad
        xi = pltpu.bitcast(flat, jnp.int32)
        X = jnp.concatenate([xi] * 8, axis=0)
        if const_shift:
            sh = np.repeat(np.arange(8, dtype=np.int32), f // 4)[:, None]
            pb = (X >> jnp.asarray(sh)) & jnp.int32(0x01010101)
        else:
            shifts = jax.lax.broadcasted_iota(
                jnp.int32, (2 * f, t), 0
            ) // jnp.int32(f // 4)
            pb = (X >> shifts) & jnp.int32(0x01010101)
        bits = pltpu.bitcast(pb, jnp.int8)
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        acc8 = acc.astype(jnp.int8)
        p32 = pltpu.bitcast(acc8, jnp.int32)
        masked = p32 & jnp.int32(0x01010101)
        nib = (
            masked
            | (masked >> jnp.int32(7))
            | (masked >> jnp.int32(14))
            | (masked >> jnp.int32(21))
        ) & jnp.int32(0xF)
        sr = s * r
        out32 = nib[0:sr] | (nib[sr : 2 * sr] << jnp.int32(4))
        out_ref[:] = out32.astype(jnp.uint8).reshape(s, r, t)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("c", "r", "s", "pad", "tile", "cshift")
)
def _var_apply(bmat_big, data, c, r, s, pad, tile, cshift):
    batch, _, n = data.shape
    return pl.pallas_call(
        _make_kernel(c, r, s, pad, cshift),
        grid=(batch // s, n // tile),
        in_specs=[
            pl.BlockSpec(bmat_big.shape, lambda b, ch: (0, 0)),
            pl.BlockSpec((s, c, tile), lambda b, ch: (b, 0, ch)),
        ],
        out_specs=pl.BlockSpec((s, r, tile), lambda b, ch: (b, 0, ch)),
        out_shape=jax.ShapeDtypeStruct((batch, r, n), jnp.uint8),
    )(bmat_big, data)


def variant(bmat_np, k, m, s, pad, tile, cshift):
    big = jnp.asarray(pe._v3_matrix(bmat_np, k, m, s, pad))
    return lambda d: _var_apply(big, d, k, m, s, pad, tile, cshift)


def main() -> None:
    args = sys.argv[1:]
    k = int(args[0]) if args else 10
    m = int(args[1]) if len(args) > 1 else 4

    g = cauchy_good_matrix(k, m)
    bmat_np = gf_matrix_to_bitmatrix(g[k:, :])
    rng = np.random.default_rng(0)
    data = jnp.asarray(
        rng.integers(0, 256, (BATCH, k, CHUNK), np.uint8)
    )
    small = jnp.asarray(rng.integers(0, 256, (8, k, 8192), np.uint8))
    from ceph_tpu.ops.bitplane import gf_encode_bitplane

    ref = np.asarray(gf_encode_bitplane(jnp.asarray(bmat_np), small))

    # variants: (name, s, pad, tile, cshift)
    cands = []
    for s in (1, 2, 4):
        if BATCH % s:
            continue
        base = s * k
        for target in (base + (-base) % 4, 16, 24, 32, 48, 64):
            pad = target - base
            if pad < 0 or pad > 24:
                continue
            if (base + pad) % 4:
                continue
            for cshift in (False, True):
                for tile in (32768, 65536):
                    cands.append((s, pad, tile, cshift))
    seen = set()
    print(f"k={k} m={m}  cur={_gbps(lambda d: pe.gf_encode_bitplane_pallas(bmat_np, d), data, k):.1f} GB/s")
    for s, pad, tile, cshift in cands:
        key = (s, pad, tile, cshift)
        if key in seen:
            continue
        seen.add(key)
        f = s * k + pad
        name = f"s{s} F={f} tile={tile//1024}k cs={int(cshift)}"
        try:
            got = np.asarray(
                variant(bmat_np, k, m, s, pad, 2048, cshift)(small)
            )
            if not np.array_equal(got, ref):
                print(f"{name}: WRONG")
                continue
            fn = variant(bmat_np, k, m, s, pad, tile, cshift)
            gb = _gbps(fn, data, k)
            print(f"{name}: {gb:.1f} GB/s")
        except Exception as e:
            print(f"{name}: fail {type(e).__name__} {str(e)[:80]}")


if __name__ == "__main__":
    main()
