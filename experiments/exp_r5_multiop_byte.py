"""Round-5: does the multi-operand (per-shard 2D) form speed up the
BYTE-code MXU kernel the way it did the XOR-schedule kernel?

The flagship path feeds the v3 kernel a stacked [B, 8, 1M] tensor
whose minor dims (8, 1M) underfill the uint8 (32,128) tile — if the
DMA pays that padding, per-shard [B, 1M] operands (dense) with an
in-kernel concat should run substantially faster.

Honest harness: feedback loop (out patches next input), device PRNG
data, diff-of-minima.
"""

import functools
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ceph_tpu.gf import gf_matrix_to_bitmatrix, vandermonde_rs_matrix
from ceph_tpu.ops import pallas_encode as pe
from ceph_tpu.ops.pallas_encode import unpack_bitplanes, _v3_matrix


def timed(fn, *args):
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def loop_stats(loop, data, target=0.45, reps=4):
    base = min(timed(loop, data, 1) for _ in range(2))
    n2 = 60
    while n2 < 40000:
        if timed(loop, data, n2) - base >= target:
            break
        n2 *= 2
    n1 = max(1, n2 // 10)
    t1 = min(timed(loop, data, n1) for _ in range(reps))
    t2 = min(timed(loop, data, n2) for _ in range(reps))
    return (t2 - t1) / (n2 - n1)


def dev_rand(shape, seed):
    key = jax.random.PRNGKey(seed)
    return jax.random.randint(key, shape, 0, 256, jnp.int32).astype(
        jnp.uint8
    )


K, M = 8, 4
CHUNK = 1 << 20
BATCH = 8


def make_multiop_byte(bitmatrix, k, m, s, tile):
    """Per-shard operands, v3 math inside: concat shard rows ->
    unpack -> stationary matmul -> nibble pack -> m parity refs."""
    from jax.experimental.pallas import tpu as pltpu

    c = k
    pad = (-s * c) % 4 if s * c > 16 else (0 if (s*c) % 4 == 0 else (-s*c) % 4)
    # match _pick_stripes((8), batch even): s=2, pad 0 -> F=16
    big = _v3_matrix(np.asarray(bitmatrix, np.uint8), c, m, s, pad)

    def kernel(bmat_ref, *refs):
        ins, outs = refs[:k], refs[k:]
        t = ins[0].shape[1]
        # [S*C, T]: shard-major rows per stripe (si*c + i) — the v3
        # matrix's bits-col order (b*(s*c+pad) + si*c + i)
        rows = []
        for si in range(s):
            for i in range(c):
                rows.append(ins[i][si : si + 1, :])
        flat = jnp.concatenate(rows, axis=0)
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad, t), jnp.uint8)], axis=0
            )
        bits = unpack_bitplanes(flat, False)
        acc = jax.lax.dot_general(
            bmat_ref[:], bits, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        acc8 = acc.astype(jnp.int8)
        p32 = pltpu.bitcast(acc8, jnp.int32)
        masked = p32 & jnp.int32(0x01010101)
        nib = (
            masked | (masked >> jnp.int32(7)) | (masked >> jnp.int32(14))
            | (masked >> jnp.int32(21))
        ) & jnp.int32(0xF)
        sr = s * m
        out32 = nib[0:sr] | (nib[sr : 2 * sr] << jnp.int32(4))
        out8 = out32.astype(jnp.uint8).reshape(s, m, t)
        for j in range(m):
            outs[j][:, :] = out8[:, j, :]

    @jax.jit
    def apply(*shards):
        b, n = shards[0].shape
        return pl.pallas_call(
            kernel,
            grid=(b // s, n // tile),
            in_specs=[pl.BlockSpec(big.shape, lambda i, c2: (0, 0))]
            + [
                pl.BlockSpec((s, tile), lambda i, c2: (i, c2))
                for _ in range(k)
            ],
            out_specs=[
                pl.BlockSpec((s, tile), lambda i, c2: (i, c2))
                for _ in range(m)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, n), jnp.uint8)
                for _ in range(m)
            ],
        )(big, *shards)

    return apply


def build_loop_shards(apply):
    @jax.jit
    def loop(arrs, iters):
        def body(i, carry):
            arrs, acc = carry
            outs = apply(*arrs)
            fold = jax.lax.dynamic_slice(outs[0], (0, 0), (1, 128))
            first = jax.lax.dynamic_update_slice(
                arrs[0], fold ^ jnp.uint8(i + 1), (0, 0)
            )
            return (first,) + arrs[1:], acc ^ fold[0, 0]

        _, acc = jax.lax.fori_loop(0, iters, body, (arrs, jnp.uint8(0)))
        return acc

    return loop


def build_loop_stacked(apply):
    @jax.jit
    def loop(d0, iters):
        def body(i, carry):
            d, acc = carry
            out = apply(d)
            fold = jax.lax.dynamic_slice(out, (0, 0, 0), (1, 1, 128))
            d = jax.lax.dynamic_update_slice(
                d, fold ^ jnp.uint8(i + 1), (0, 0, 0)
            )
            return d, acc ^ fold[0, 0, 0]

        _, acc = jax.lax.fori_loop(0, iters, body, (d0, jnp.uint8(0)))
        return acc

    return loop


def main():
    g = vandermonde_rs_matrix(K, M)
    bmat = gf_matrix_to_bitmatrix(g[K:, :])
    nbytes = BATCH * K * CHUNK

    # current path: stacked [B, K, N]
    data = dev_rand((BATCH, K, CHUNK), 0)
    loop = build_loop_stacked(
        lambda d: pe.gf_encode_bitplane_pallas(bmat, d)
    )
    per = loop_stats(loop, data)
    print(f"stacked v3: {nbytes/per/1e9:.1f} GB/s data-in", flush=True)

    # correctness of the multi-op form first (tiny shapes)
    small = tuple(dev_rand((4, 8192), 10 + i) for i in range(K))
    ap = make_multiop_byte(bmat, K, M, 2, 8192)
    outs = ap(*small)
    stacked_small = jnp.stack(small, axis=1)
    want = pe.gf_encode_bitplane_pallas(bmat, stacked_small)
    ok = all(
        np.array_equal(np.asarray(outs[j]), np.asarray(want[:, j, :]))
        for j in range(M)
    )
    print("multiop matches v3:", ok, flush=True)

    for tile in (32768, 65536):
        shards = tuple(dev_rand((BATCH, CHUNK), 20 + i) for i in range(K))
        ap = make_multiop_byte(bmat, K, M, 2, tile)
        loop = build_loop_shards(ap)
        per = loop_stats(loop, shards)
        print(
            f"multiop s=2 tile={tile}: {nbytes/per/1e9:.1f} GB/s data-in",
            flush=True,
        )


if __name__ == "__main__":
    main()
