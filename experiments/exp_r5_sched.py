"""Round-5: schedule-native XOR encode for the packet codes.

Parity packet q = XOR of the data packets its 0/1 matrix row selects
(~k+1 terms for liberation-family rows). Pure VPU/HBM work, no MXU,
no bit unpack. Candidate forms:

  xor8   : unrolled jnp xor chains on uint8 rows
  xor32  : same but operands bitcast to int32 lanes first
  pallas : one pallas kernel, block over (batch, lane-tile), xor in VMEM

Measured on the exact r4 bench geometry ([32, 4, 7*32768] liberation)
plus larger shapes.
"""

import functools
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def loop_gbps(apply, data, n1=100, n2=4100, reps=4, opaque=False):
    """Diff-of-minima: time t(n1) and t(n2) `reps` times each, take the
    min of each (tunnel hiccups only ADD time, so per-count minima are
    clean), then diff. Non-opaque (plain-XLA) applies fold the FULL
    output or XLA dead-codes the work through the 128-byte slice."""
    batch, k, n = data.shape

    @jax.jit
    def loop(d0, iters):
        def body(i, carry):
            d, acc = carry
            patch = (
                jax.lax.dynamic_slice(d, (0, 0, 0), (1, 1, 128))
                ^ jnp.uint8(i + 1)
            )
            d = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
            out = apply(d)
            if opaque:
                fold = jax.lax.dynamic_slice(
                    out, (0, 0, 0), (1, 1, 128)
                )[0, 0, 0]
            else:
                fold = jnp.sum(out, dtype=jnp.uint8)
            return d, acc ^ fold

        _, acc = jax.lax.fori_loop(0, iters, body, (d0, jnp.uint8(0)))
        return acc

    def timed(iters):
        t0 = time.perf_counter()
        np.asarray(loop(data, iters))
        return time.perf_counter() - t0

    for t in (n1, n2):
        timed(t)
    t1 = min(timed(n1) for _ in range(reps))
    t2 = min(timed(n2) for _ in range(reps))
    dt = (t2 - t1) / (n2 - n1)
    if dt <= 0:
        return float("nan")
    return batch * k * n / dt / 1e9


def xor8_apply(sel_rows, packets):
    """packets [B, KW, P]; sel_rows: tuple of tuples of column idx."""
    outs = []
    for sel in sel_rows:
        acc = packets[..., sel[0], :]
        for j in sel[1:]:
            acc = acc ^ packets[..., j, :]
        outs.append(acc)
    return jnp.stack(outs, axis=-2)


def xor32_apply(sel_rows, packets):
    b, kw, p = packets.shape
    pk = jax.lax.bitcast_convert_type(
        packets.reshape(b, kw, p // 4, 4), jnp.int32
    )
    outs = []
    for sel in sel_rows:
        acc = pk[..., sel[0], :]
        for j in sel[1:]:
            acc = acc ^ pk[..., j, :]
        outs.append(acc)
    out = jnp.stack(outs, axis=-2)
    return jax.lax.bitcast_convert_type(out, jnp.uint8).reshape(
        b, len(sel_rows), p
    )


def make_pallas_sched(sel_rows, kw, lane_tile, s=1):
    mw = len(sel_rows)

    def kernel(d_ref, o_ref):
        d = d_ref[:]  # [S, KW, T] uint8
        for q, sel in enumerate(sel_rows):
            acc = d[:, sel[0], :]
            for j in sel[1:]:
                acc = acc ^ d[:, j, :]
            o_ref[:, q, :] = acc

    @jax.jit
    def apply(packets):
        b, _, p = packets.shape
        return pl.pallas_call(
            kernel,
            grid=(b // s, p // lane_tile),
            in_specs=[pl.BlockSpec((s, kw, lane_tile), lambda i, c: (i, 0, c))],
            out_specs=pl.BlockSpec((s, mw, lane_tile), lambda i, c: (i, 0, c)),
            out_shape=jax.ShapeDtypeStruct((b, mw, p), jnp.uint8),
        )(packets)

    return apply


def main():
    rng = np.random.default_rng(11)
    from ceph_tpu.codecs import registry

    codec = registry.factory(
        "jerasure", {"technique": "liberation", "k": "4", "m": "2", "w": "7"}
    )
    mat = np.asarray(codec.coding_bitmatrix)  # [mw, kw] 0/1
    mw, kw = mat.shape
    sel_rows = tuple(
        tuple(int(j) for j in np.flatnonzero(mat[q])) for q in range(mw)
    )
    ones = sum(len(s) for s in sel_rows)
    print(f"liberation k4 m2 w7: mat {mat.shape}, {ones} ones "
          f"(avg {ones/mw:.1f}/row)", flush=True)

    shapes = [(32, kw, 32768)]
    for shape in shapes:
        data = jnp.asarray(rng.integers(0, 256, shape, np.uint8))
        for s in (1, 2, 4, 8):
            if shape[0] % s:
                continue
            for tile in (8192, 32768):
                if shape[2] % tile:
                    continue
                gp = loop_gbps(
                    make_pallas_sched(sel_rows, kw, tile, s), data,
                    opaque=True,
                )
                print(f"pallas s={s} t={tile} {shape}: {gp:.1f} GB/s",
                      flush=True)

    # sanity: all three agree with the codec's own encode
    data = jnp.asarray(rng.integers(0, 256, (4, kw, 4096), np.uint8))
    ref = np.asarray(
        jnp.stack(
            [v for _, v in sorted(
                codec.encode_chunks(
                    {i: np.asarray(data).reshape(4, 4, kw // 4 * 4096)[:, i, :]
                     for i in range(4)}
                ).items()
            )], axis=1)
    ) if False else None
    a = np.asarray(xor8_apply(sel_rows, data))
    b = np.asarray(xor32_apply(sel_rows, data))
    c = np.asarray(make_pallas_sched(sel_rows, kw, 4096)(data))
    print("agree:", np.array_equal(a, b), np.array_equal(a, c), flush=True)


if __name__ == "__main__":
    main()
