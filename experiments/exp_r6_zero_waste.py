"""Round-6: zero-waste packing sweep — stripes on grid/lanes vs the
round-5 block-diagonal stripe pair.

The production kernels (ops/pallas_encode.py) now batch stripes on
the grid and lane axes with the bare [8R, 8F] code matrix; this
script sweeps the remaining knob — the lane batch S (stripes merged
along lanes per grid step) — per bench geometry, against the old
block-diagonal comparator rebuilt inline. Run on the v5e tunnel:

    python experiments/exp_r6_zero_waste.py

Off-TPU it falls back to interpreter mode on tiny shapes (correctness
smoke only; the timings mean nothing there).

MAC accounting (mac_stats): at (8,4) the zero-waste layout clocks
256 MACs/byte, all useful; the r5 pair clocked 512 at useful=0.5. If
the flagship was MXU-throughput-bound at mxu_util 0.761, halving
clocked MACs should land encode near 400+ GB/s data-in — the VERDICT
r6 item-2 target this sweep is meant to confirm or refute per S.
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np
import jax
import jax.numpy as jnp

from ceph_tpu.gf import (
    cauchy_good_matrix,
    gf_matrix_to_bitmatrix,
    vandermonde_rs_matrix,
)
from ceph_tpu.ops import pallas_encode as pe

# helpers duplicated from exp_r5_multiop_byte rather than imported:
# that module builds the removed round-5 block-diagonal matrices at
# import time and is kept as the historical record of that design


def timed(fn, *args):
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def loop_stats(loop, data, target=0.45, reps=4):
    base = min(timed(loop, data, 1) for _ in range(2))
    n2 = 60
    while n2 < 40000:
        if timed(loop, data, n2) - base >= target:
            break
        n2 *= 2
    n1 = max(1, n2 // 10)
    t1 = min(timed(loop, data, n1) for _ in range(reps))
    t2 = min(timed(loop, data, n2) for _ in range(reps))
    return (t2 - t1) / (n2 - n1)


def dev_rand(shape, seed):
    key = jax.random.PRNGKey(seed)
    return jax.random.randint(key, shape, 0, 256, jnp.int32).astype(
        jnp.uint8
    )


def build_loop_stacked(apply):
    """Feedback loop over [B, C, N]: output slice patches the input."""

    @jax.jit
    def loop(d0, iters):
        def body(i, carry):
            d, acc = carry
            out = apply(d)
            fold = jax.lax.dynamic_slice(
                out, (0, 0, 0), (1, 1, 128)
            )
            d = jax.lax.dynamic_update_slice(
                d, fold ^ jnp.uint8(i + 1), (0, 0, 0)
            )
            return d, acc ^ fold[0, 0, 0]

        _, acc = jax.lax.fori_loop(0, iters, body, (d0, jnp.uint8(0)))
        return acc

    return loop

#: (name, generator, k, m, chunk, stripes) — the bench geometries the
#: repack targets (BENCH_r05: flagship 293, jerasure 131.5, cauchy
#: 147.9 GB/s)
CONFIGS = [
    ("flagship_k8m4_1m", vandermonde_rs_matrix, 8, 4, 1 << 20, 8),
    ("jerasure_k4m2_4k", vandermonde_rs_matrix, 4, 2, 4096, 4096),
    ("cauchy_k10m4_100k", cauchy_good_matrix, 10, 4, 102400, 256),
]


def sweep_lane_batch(bmat, data, s_values):
    """Force each lane batch S through the production kernel by
    monkey-patching the picker; returns {S: GB/s}."""
    out = {}
    batch, k, n = data.shape
    orig = pe._pick_lane_batch
    for s in s_values:
        if batch % s:
            continue
        pe._pick_lane_batch = lambda b, t, _s=s: _s
        try:
            apply = lambda d: pe.gf_encode_bitplane_pallas(bmat, d)
            loop = build_loop_stacked(apply)
            per = loop_stats(loop, data)
            out[s] = batch * k * n / per / 1e9
        except Exception as e:
            out[s] = f"{type(e).__name__}: {str(e)[:80]}"
        finally:
            pe._pick_lane_batch = orig
    return out


def main():
    on_tpu = pe.on_tpu()
    if not on_tpu:
        print("off-TPU: interpreter-mode smoke on tiny shapes")
    for name, gen, k, m, chunk, stripes in CONFIGS:
        if not on_tpu:
            chunk, stripes = pe.LANE_TILE, 8
        g = np.asarray(gen(k, m))
        bmat = gf_matrix_to_bitmatrix(g[k:, :])
        data = dev_rand((stripes, k, chunk), 7)
        if not on_tpu:
            from ceph_tpu.ops.bitplane import gf_encode_bitplane

            ref = np.asarray(
                gf_encode_bitplane(jnp.asarray(bmat), data)
            )
            got = np.asarray(
                pe.gf_encode_bitplane_pallas(bmat, data, interpret=True)
            )
            print(name, "interpret bit-exact:", (ref == got).all())
            continue
        stats = pe.mac_stats(k, m)
        print(f"== {name}: useful_frac={stats['useful_frac']:.3f}, "
              f"{stats['macs_per_byte']:.0f} MACs/byte")
        for s, gbps in sweep_lane_batch(bmat, data, (1, 2, 4, 8)).items():
            if isinstance(gbps, float):
                print(f"  S={s}: {gbps:7.1f} GB/s data-in")
            else:
                print(f"  S={s}: {gbps}")


if __name__ == "__main__":
    main()
