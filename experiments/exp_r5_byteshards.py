"""Round-5: shards-form MXU kernel sweep — stripes-per-block (s) and
geometry. Follows exp_r5_multiop_byte.py; adds the s sweep (F = s*c up
to 32 — exp_highk measured the column stream fastest at F=32) and the
SHEC/LRC bench geometry ([256, 64 KiB] shards, c=4) where the stacked
path pays a 3.5x relayout (prof: raw 132 / stacked 38 / codec 27).
"""

import sys

sys.path.insert(0, ".")

import numpy as np
import jax
import jax.numpy as jnp

from ceph_tpu.gf import gf_matrix_to_bitmatrix, vandermonde_rs_matrix
from ceph_tpu.ops import pallas_encode as pe
from experiments.exp_r5_multiop_byte import (
    build_loop_shards,
    build_loop_stacked,
    dev_rand,
    loop_stats,
    make_multiop_byte,
)


def sweep(k, m, batch, chunk, tiles, ss):
    g = vandermonde_rs_matrix(k, m)
    bmat = gf_matrix_to_bitmatrix(g[k:, :])
    nbytes = batch * k * chunk

    data = dev_rand((batch, k, chunk), 0)
    loop = build_loop_stacked(lambda d: pe.gf_encode_bitplane_pallas(bmat, d))
    per = loop_stats(loop, data)
    print(f"  stacked v3 auto: {nbytes/per/1e9:.1f} GB/s", flush=True)

    small = tuple(dev_rand((8, 8192), 10 + i) for i in range(k))
    stacked_small = jnp.stack(small, axis=1)
    want = pe.gf_encode_bitplane_pallas(bmat, stacked_small)
    shards = tuple(dev_rand((batch, chunk), 20 + i) for i in range(k))
    for s in ss:
        if batch % s:
            continue
        ap = make_multiop_byte(bmat, k, m, s, 8192)
        outs = ap(*small)
        ok = all(
            np.array_equal(np.asarray(outs[j]), np.asarray(want[:, j, :]))
            for j in range(m)
        )
        for tile in tiles:
            if chunk % tile:
                continue
            try:
                ap = make_multiop_byte(bmat, k, m, s, tile)
                loop = build_loop_shards(ap)
                per = loop_stats(loop, shards)
                print(
                    f"  multiop s={s} F={s*k} tile={tile}: "
                    f"{nbytes/per/1e9:.1f} GB/s ok={ok}",
                    flush=True,
                )
            except Exception as e:
                print(f"  multiop s={s} tile={tile}: {type(e).__name__} "
                      f"{str(e)[:80]}", flush=True)


def main():
    print("flagship (8,4) batch=8 chunk=1M:", flush=True)
    sweep(8, 4, 8, 1 << 20, (32768, 65536), (2, 4, 8))
    print("shec-geom (4,3) batch=256 chunk=64K:", flush=True)
    sweep(4, 3, 256, 65536, (16384, 32768, 65536), (2, 4, 8, 16))
    print("lrc-local (2,1) batch=256 chunk=64K:", flush=True)
    sweep(2, 1, 256, 65536, (32768, 65536), (2, 4, 8, 16))


if __name__ == "__main__":
    main()
