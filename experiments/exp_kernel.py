"""Kernel roofline experiments (round 3, not part of the package).

Measures, with bench.py's on-device-loop + trip-count-differencing
methodology:
  copy   — DMA-only probe (same grid/blockspecs, out = xor of rows):
           the achievable ceiling for this traffic pattern
  cur    — the shipping kernel (ops/pallas_encode.py)
  v3     — packed-int32 unpack (bitcast, (x>>b)&0x01010101) + plane
           matmul + matmul-based byte pack (W weights 2^b, -128 for b7)

Usage: PYTHONPATH=/root/repo python exp_kernel.py [variants...]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ceph_tpu.gf import gf_matrix_to_bitmatrix, vandermonde_rs_matrix
from ceph_tpu.ops import pallas_encode as pe
from ceph_tpu.ops.bitplane import gf_encode_bitplane

K, M = 8, 4
CHUNK = 1 << 20
BATCH = 8
N1, N2 = 10, 110
REPS = 5


def _timed(fn, *args) -> float:
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def _per_iter(fn, *args) -> float:
    diffs = []
    for _ in range(REPS):
        d = (_timed(fn, *args, N2) - _timed(fn, *args, N1)) / (N2 - N1)
        if d > 0:
            diffs.append(d)
    return float(np.median(diffs)) if diffs else float("nan")


def _loop(apply, out_shards):
    @jax.jit
    def loop(data, iters):
        def body(i, carry):
            d, acc = carry
            d = jnp.bitwise_xor(d, jnp.uint8(i + 1))
            return d, jnp.bitwise_xor(acc, apply(d))

        _, acc = jax.lax.fori_loop(
            0, iters, body,
            (data, jnp.zeros((BATCH, out_shards, CHUNK), jnp.uint8)),
        )
        return acc[0, 0, 0]

    return loop


@jax.jit
def _loop_perturb(data, iters):
    def body(i, carry):
        d, acc = carry
        d = jnp.bitwise_xor(d, jnp.uint8(i + 1))
        return d, jnp.bitwise_xor(acc, d[:, :M, :])

    _, acc = jax.lax.fori_loop(
        0, iters, body,
        (data, jnp.zeros((BATCH, M, CHUNK), jnp.uint8)),
    )
    return acc[0, 0, 0]


# ---------------------------------------------------------------- copy probe
def _copy_kernel(data_ref, out_ref):
    d = data_ref[0]
    out_ref[0] = d[0:M] ^ d[M : 2 * M]


@functools.partial(jax.jit, static_argnames=("lane_tile",))
def copy_probe(data, lane_tile):
    b, k, n = data.shape
    return pl.pallas_call(
        _copy_kernel,
        grid=(b, n // lane_tile),
        in_specs=[pl.BlockSpec((1, k, lane_tile), lambda b, c: (b, 0, c))],
        out_specs=pl.BlockSpec((1, M, lane_tile), lambda b, c: (b, 0, c)),
        out_shape=jax.ShapeDtypeStruct((b, M, n), jnp.uint8),
    )(data)


# ------------------------------------------------------------------ v3 kernel
def _pack_weights(m: int) -> np.ndarray:
    """W[j, b*m+j] = 2^b as int8 (-128 stands for 128; the final
    int32->uint8 convert wraps mod 256, recovering the true byte)."""
    w = np.zeros((m, 8 * m), np.int8)
    for b in range(8):
        for j in range(m):
            w[j, b * m + j] = (1 << b) if b < 7 else -128
    return w


def _make_v3_kernel(k: int, m: int):
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bmat_ref, wmat_ref, data_ref, out_ref):
        d = data_ref[0]  # [K, T] uint8
        # Sublane bitcast: 4 uint8 rows pack into one int32 row. The
        # shift+mask keeps each byte's bit in its own byte lane, and
        # the bitcast back scatters byte lanes to the sublanes they
        # came from — row order is self-consistent either way.
        xi = pltpu.bitcast(d, jnp.int32)  # [K/4, T]
        planes = []
        for b in range(8):
            pb = (xi >> jnp.int32(b)) & jnp.int32(0x01010101)
            planes.append(pltpu.bitcast(pb, jnp.int8))  # [K, T] plane b
        bits = jnp.concatenate(planes, axis=0)  # [8K, T] plane-major
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [8M, T]
        acc8 = acc.astype(jnp.int8) & jnp.int8(1)
        packed = jax.lax.dot_general(
            wmat_ref[:], acc8,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [M, T]
        out_ref[0] = packed.astype(jnp.uint8)

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "m", "lane_tile"))
def v3_encode(bmat_pm, wmat, data, k, m, lane_tile):
    b, _, n = data.shape
    return pl.pallas_call(
        _make_v3_kernel(k, m),
        grid=(b, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat_pm.shape, lambda b, c: (0, 0)),
            pl.BlockSpec(wmat.shape, lambda b, c: (0, 0)),
            pl.BlockSpec((1, k, lane_tile), lambda b, c: (b, 0, c)),
        ],
        out_specs=pl.BlockSpec((1, m, lane_tile), lambda b, c: (b, 0, c)),
        out_shape=jax.ShapeDtypeStruct((b, m, n), jnp.uint8),
    )(bmat_pm, wmat, data)


# ---------------------------------------------------- v4: multi-stripe tiles
# Pack S=8 stripes per block so every intermediate fills native vreg
# tiles: d [S*K=64, T'] uint8 (2 int8 tiles), xi [16, T'] int32 (2
# tiles), bits [512, T'], acc [256, T'] — no partial-tile waste. The
# matmul is block-diagonal over stripes (host-built sparse matrix).

S = 8  # stripes per block


def _vS_matrices(bmat_np: np.ndarray, k: int, m: int, s_count: int):
    """Generalized block-diag matrices for s_count stripes.
    bits row (s, b, i) = s*8*k + b*k + i  (stripe-major blocks so the
    contraction splits cleanly at 128); acc row (s, b', j); out row
    (s, j)."""
    bb = np.zeros((8 * s_count * m, 8 * s_count * k), np.int8)
    for s in range(s_count):
        for bp in range(8):
            for b in range(8):
                for j in range(m):
                    for i in range(k):
                        bb[
                            s * 8 * m + bp * m + j,
                            s * 8 * k + b * k + i,
                        ] = bmat_np[j * 8 + bp, i * 8 + b]
    wb = np.zeros((s_count * m, 8 * s_count * m), np.int8)
    for s in range(s_count):
        for bp in range(8):
            v = (1 << bp) if bp < 7 else -128
            for j in range(m):
                wb[s * m + j, s * 8 * m + bp * m + j] = v
    return bb, wb


def _make_v5_kernel(k: int, m: int, s_count: int):
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bmat_ref, wmat_ref, data_ref, out_ref):
        d = data_ref[:]  # [S2, K, T'] uint8
        t = d.shape[2]
        flat = d.reshape(s_count * k, t)  # row s*k+i
        xi = pltpu.bitcast(flat, jnp.int32)  # [S2*k/4, T']
        planes = []
        for b in range(8):
            pb = (xi >> jnp.int32(b)) & jnp.int32(0x01010101)
            planes.append(pltpu.bitcast(pb, jnp.int8))  # [S2*k, T']
        # bits row (s, b, i): stack planes then interleave stripes to
        # stripe-major via reshape/transpose-free indexing: build by
        # slicing each plane's stripe rows.
        per_stripe = []
        for s in range(s_count):
            for b in range(8):
                per_stripe.append(planes[b][s * k : (s + 1) * k])
        bits = jnp.concatenate(per_stripe, axis=0)  # [8*S2*k, T']
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [8*S2*m, T']
        acc8 = acc.astype(jnp.int8) & jnp.int8(1)
        packed = jax.lax.dot_general(
            wmat_ref[:], acc8,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [S2*m, T']
        out_ref[:] = packed.astype(jnp.uint8).reshape(s_count, m, t)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("k", "m", "lane_tile", "s_count")
)
def v5_encode(bmat_big, wmat_big, data, k, m, lane_tile, s_count=2):
    b, _, n = data.shape
    return pl.pallas_call(
        _make_v5_kernel(k, m, s_count),
        grid=(b // s_count, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat_big.shape, lambda b, c: (0, 0)),
            pl.BlockSpec(wmat_big.shape, lambda b, c: (0, 0)),
            pl.BlockSpec(
                (s_count, k, lane_tile), lambda b, c: (b, 0, c)
            ),
        ],
        out_specs=pl.BlockSpec(
            (s_count, m, lane_tile), lambda b, c: (b, 0, c)
        ),
        out_shape=jax.ShapeDtypeStruct((b, m, n), jnp.uint8),
    )(bmat_big, wmat_big, data)


# ------------------- v6: plane-major columns (no slice-interleave concat)
def _v6_matrices(bmat_np: np.ndarray, k: int, m: int, s_count: int):
    """Column order (b, s, i) = concat(planes) order — the stripe
    interleave lives in the matrix, not the data. Rows (s, b', j) so
    the pack matmul stays block-diag per stripe."""
    bb = np.zeros((8 * s_count * m, 8 * s_count * k), np.int8)
    for s in range(s_count):
        for bp in range(8):
            for b in range(8):
                for j in range(m):
                    for i in range(k):
                        bb[
                            s * 8 * m + bp * m + j,
                            b * s_count * k + s * k + i,
                        ] = bmat_np[j * 8 + bp, i * 8 + b]
    wb = np.zeros((s_count * m, 8 * s_count * m), np.int8)
    for s in range(s_count):
        for bp in range(8):
            v = (1 << bp) if bp < 7 else -128
            for j in range(m):
                wb[s * m + j, s * 8 * m + bp * m + j] = v
    return bb, wb


def _make_v6_kernel(k: int, m: int, s_count: int, ablate: str = ""):
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bmat_ref, wmat_ref, data_ref, out_ref):
        d = data_ref[:]  # [S, K, T'] uint8
        t = d.shape[2]
        flat = d.reshape(s_count * k, t)
        xi = pltpu.bitcast(flat, jnp.int32)
        planes = []
        for b in range(8):
            pb = (xi >> jnp.int32(b)) & jnp.int32(0x01010101)
            planes.append(pltpu.bitcast(pb, jnp.int8))  # [S*k, T']
        if ablate == "planes":
            o = planes[0]
            for b in range(1, 8):
                o = o ^ planes[b]
            out_ref[:] = (
                o[: s_count * m, :].astype(jnp.uint8).reshape(s_count, m, t)
            )
            return
        bits = jnp.concatenate(planes, axis=0)  # [8*S*k, T'] (b,s,i)
        if ablate == "bits":
            o = bits[: s_count * m] ^ bits[64 : 64 + s_count * m]
            out_ref[:] = o.astype(jnp.uint8).reshape(s_count, m, t)
            return
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [8*S*m, T']
        if ablate == "mm":
            out_ref[:] = (
                acc[: s_count * m].astype(jnp.uint8).reshape(s_count, m, t)
            )
            return
        acc8 = acc.astype(jnp.int8) & jnp.int8(1)
        packed = jax.lax.dot_general(
            wmat_ref[:], acc8,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        out_ref[:] = packed.astype(jnp.uint8).reshape(s_count, m, t)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("k", "m", "lane_tile", "s_count", "ablate")
)
def v6_encode(bmat_big, wmat_big, data, k, m, lane_tile, s_count=2, ablate=""):
    b, _, n = data.shape
    return pl.pallas_call(
        _make_v6_kernel(k, m, s_count, ablate),
        grid=(b // s_count, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat_big.shape, lambda b, c: (0, 0)),
            pl.BlockSpec(wmat_big.shape, lambda b, c: (0, 0)),
            pl.BlockSpec(
                (s_count, k, lane_tile), lambda b, c: (b, 0, c)
            ),
        ],
        out_specs=pl.BlockSpec(
            (s_count, m, lane_tile), lambda b, c: (b, 0, c)
        ),
        out_shape=jax.ShapeDtypeStruct((b, m, n), jnp.uint8),
    )(bmat_big, wmat_big, data)


# --- v9: v6 structure (S=2, one [64,128] matmul) + nibble-bitcast pack.
def _v9_matrices(bmat_np: np.ndarray, k: int, m: int):
    """bmat [64, 128] int8: acc row r = h*32 + s*16 + j*4 + b2 (b' =
    h*4 + b2); col (b, s, i) = b*16 + s*8 + i (concat(planes) order,
    S=2)."""
    mat = np.zeros((64, 128), np.int8)
    for h in range(2):
        for s in range(2):
            for j in range(m):
                for b2 in range(4):
                    bp = h * 4 + b2
                    r = h * 32 + s * 16 + j * 4 + b2
                    for b in range(8):
                        for i in range(k):
                            mat[r, b * 16 + s * 8 + i] = bmat_np[
                                j * 8 + bp, i * 8 + b
                            ]
    return mat


def _make_v9_kernel(k: int, m: int, i32concat: bool = False):
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bmat_ref, data_ref, out_ref):
        d = data_ref[:]  # [2, K, T'] uint8
        t = d.shape[2]
        flat = d.reshape(2 * k, t)
        xi = pltpu.bitcast(flat, jnp.int32)  # [4, T']
        if i32concat:
            p32 = [
                (xi >> jnp.int32(b)) & jnp.int32(0x01010101)
                for b in range(8)
            ]
            bits = pltpu.bitcast(
                jnp.concatenate(p32, axis=0), jnp.int8
            )  # [128, T'] (b, s, i)
        else:
            planes = []
            for b in range(8):
                pb = (xi >> jnp.int32(b)) & jnp.int32(0x01010101)
                planes.append(pltpu.bitcast(pb, jnp.int8))  # [16, T']
            bits = jnp.concatenate(planes, axis=0)  # [128, T'] (b, s, i)
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [64, T'] rows (h, s, j, b2)
        acc8 = acc.astype(jnp.int8)
        p32 = pltpu.bitcast(acc8, jnp.int32)  # [16, T']
        masked = p32 & jnp.int32(0x01010101)
        nib = (
            masked
            | (masked >> jnp.int32(7))
            | (masked >> jnp.int32(14))
            | (masked >> jnp.int32(21))
        ) & jnp.int32(0xF)
        out32 = nib[0:8] | (nib[8:16] << jnp.int32(4))  # [8, T']
        out_ref[:] = out32.astype(jnp.uint8).reshape(2, m, t)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("k", "m", "lane_tile", "i32concat", "dimsem")
)
def v9_encode(bmat, data, k, m, lane_tile, i32concat=False, dimsem=False):
    from jax.experimental.pallas import tpu as pltpu

    b, _, n = data.shape
    params = {}
    if dimsem:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        )
    return pl.pallas_call(
        _make_v9_kernel(k, m, i32concat),
        grid=(b // 2, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat.shape, lambda b, c: (0, 0)),
            pl.BlockSpec((2, k, lane_tile), lambda b, c: (b, 0, c)),
        ],
        out_specs=pl.BlockSpec((2, m, lane_tile), lambda b, c: (b, 0, c)),
        out_shape=jax.ShapeDtypeStruct((b, m, n), jnp.uint8),
        **params,
    )(bmat, data)


# --- v12: v9 + single variable-shift unpack (no per-plane ops, no
# --- int8 concat: the stacked int32 bitcast IS the (b,s,i) order).
def _make_v12_kernel(k: int, m: int):
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bmat_ref, data_ref, out_ref):
        d = data_ref[:]  # [2, K, T'] uint8
        t = d.shape[2]
        flat = d.reshape(2 * k, t)
        xi = pltpu.bitcast(flat, jnp.int32)  # [4, T']
        rows = 2 * k * 2  # 32
        X = jnp.concatenate([xi] * 8, axis=0)  # [32, T'] b-major
        shifts = jax.lax.broadcasted_iota(
            jnp.int32, (rows, t), 0
        ) >> jnp.int32(2)  # row r -> b = r // 4
        pb = (X >> shifts) & jnp.int32(0x01010101)
        bits = pltpu.bitcast(pb, jnp.int8)  # [128, T'] (b, s, i)
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [64, T']
        acc8 = acc.astype(jnp.int8)
        p32 = pltpu.bitcast(acc8, jnp.int32)
        masked = p32 & jnp.int32(0x01010101)
        nib = (
            masked
            | (masked >> jnp.int32(7))
            | (masked >> jnp.int32(14))
            | (masked >> jnp.int32(21))
        ) & jnp.int32(0xF)
        out32 = nib[0:8] | (nib[8:16] << jnp.int32(4))
        out_ref[:] = out32.astype(jnp.uint8).reshape(2, m, t)

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "m", "lane_tile"))
def v12_encode(bmat, data, k, m, lane_tile):
    b, _, n = data.shape
    return pl.pallas_call(
        _make_v12_kernel(k, m),
        grid=(b // 2, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat.shape, lambda b, c: (0, 0)),
            pl.BlockSpec((2, k, lane_tile), lambda b, c: (b, 0, c)),
        ],
        out_specs=pl.BlockSpec((2, m, lane_tile), lambda b, c: (b, 0, c)),
        out_shape=jax.ShapeDtypeStruct((b, m, n), jnp.uint8),
    )(bmat, data)


# --- v11: S=4, one [128, 256] matmul (Mosaic splits contraction
# --- internally, MXU accumulator sums), full-tile concat, nibble pack.
def _v11_matrices(bmat_np: np.ndarray, k: int, m: int):
    """[128, 256] int8. acc row r = h*64 + s*16 + j*4 + b2 (b' =
    h*4+b2); col (b, s, i) = b*32 + s*8 + i."""
    mat = np.zeros((128, 256), np.int8)
    for h in range(2):
        for s in range(4):
            for j in range(m):
                for b2 in range(4):
                    bp = h * 4 + b2
                    r = h * 64 + s * 16 + j * 4 + b2
                    for b in range(8):
                        for i in range(k):
                            mat[r, b * 32 + s * 8 + i] = bmat_np[
                                j * 8 + bp, i * 8 + b
                            ]
    return mat


def _make_v11_kernel(k: int, m: int, pref8: bool = False):
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bmat_ref, data_ref, out_ref):
        d = data_ref[:]  # [4, K, T'] uint8
        t = d.shape[2]
        flat = d.reshape(4 * k, t)        # [32, T'] full tile
        xi = pltpu.bitcast(flat, jnp.int32)  # [8, T'] full tile
        planes = []
        for b in range(8):
            pb = (xi >> jnp.int32(b)) & jnp.int32(0x01010101)
            planes.append(pltpu.bitcast(pb, jnp.int8))  # [32, T']
        bits = jnp.concatenate(planes, axis=0)  # [256, T'] full tiles
        if pref8:
            acc8 = jax.lax.dot_general(
                bmat_ref[:], bits,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int8,
            )
        else:
            acc = jax.lax.dot_general(
                bmat_ref[:], bits,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [128, T']
            acc8 = acc.astype(jnp.int8)
        p32 = pltpu.bitcast(acc8, jnp.int32)  # [32, T']
        masked = p32 & jnp.int32(0x01010101)
        nib = (
            masked
            | (masked >> jnp.int32(7))
            | (masked >> jnp.int32(14))
            | (masked >> jnp.int32(21))
        ) & jnp.int32(0xF)
        out32 = nib[0:16] | (nib[16:32] << jnp.int32(4))  # [16, T']
        out_ref[:] = out32.astype(jnp.uint8).reshape(4, m, t)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("k", "m", "lane_tile", "pref8")
)
def v11_encode(bmat, data, k, m, lane_tile, pref8=False):
    b, _, n = data.shape
    return pl.pallas_call(
        _make_v11_kernel(k, m, pref8),
        grid=(b // 4, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat.shape, lambda b, c: (0, 0)),
            pl.BlockSpec((4, k, lane_tile), lambda b, c: (b, 0, c)),
        ],
        out_specs=pl.BlockSpec((4, m, lane_tile), lambda b, c: (b, 0, c)),
        out_shape=jax.ShapeDtypeStruct((b, m, n), jnp.uint8),
    )(bmat, data)


# --- v8: S=4 full-tile unpack, two 128-contraction summed matmuls,
# --- bitcast-nibble pack (no second MXU stream).
def _v8_matrices(bmat_np: np.ndarray, k: int, m: int):
    """Returns (bmatA, bmatB) [128, 128] int8. acc row r = h*64 +
    s*16 + j*4 + b2 with output bit b' = h*4 + b2; bits col (within
    half) c = bh*32 + s*8 + i where plane b = half*4 + bh."""
    s_count = 4
    mats = []
    for half in range(2):
        mat = np.zeros((128, 128), np.int8)
        for h in range(2):
            for s in range(s_count):
                for j in range(m):
                    for b2 in range(4):
                        bp = h * 4 + b2
                        r = h * 64 + s * 16 + j * 4 + b2
                        for bh in range(4):
                            b = half * 4 + bh
                            for i in range(k):
                                mat[r, bh * 32 + s * 8 + i] = bmat_np[
                                    j * 8 + bp, i * 8 + b
                                ]
        mats.append(mat)
    return mats[0], mats[1]


def _make_v8_kernel(k: int, m: int):
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bmatA_ref, bmatB_ref, data_ref, out_ref):
        d = data_ref[:]  # [4, K, T'] uint8
        t = d.shape[2]
        flat = d.reshape(4 * k, t)       # [32, T'] — one full int8 tile
        xi = pltpu.bitcast(flat, jnp.int32)  # [8, T'] — full int32 tile
        planes = []
        for b in range(8):
            pb = (xi >> jnp.int32(b)) & jnp.int32(0x01010101)
            planes.append(pltpu.bitcast(pb, jnp.int8))  # [32, T']
        bits_lo = jnp.concatenate(planes[:4], axis=0)   # [128, T']
        bits_hi = jnp.concatenate(planes[4:], axis=0)   # [128, T']
        # Parity = (count_lo + count_hi) & 1 — the plane-half split
        # sums before the mod-2, so two 128-contraction passes replace
        # one 256-contraction (which Mosaic would split anyway, but
        # with a second full stream of zeros).
        acc = jax.lax.dot_general(
            bmatA_ref[:], bits_lo,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ) + jax.lax.dot_general(
            bmatB_ref[:], bits_hi,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [128, T'] rows (h, s, j, b2)
        acc8 = acc.astype(jnp.int8)               # popcounts fit int8
        p32 = pltpu.bitcast(acc8, jnp.int32)      # [32, T'] 4 rows/elt
        masked = p32 & jnp.int32(0x01010101)
        nib = (
            masked
            | (masked >> jnp.int32(7))
            | (masked >> jnp.int32(14))
            | (masked >> jnp.int32(21))
        ) & jnp.int32(0xF)                        # [32, T'] nibbles
        out32 = nib[0:16] | (nib[16:32] << jnp.int32(4))  # [16, T']
        out_ref[:] = out32.astype(jnp.uint8).reshape(4, m, t)

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "m", "lane_tile"))
def v8_encode(bmatA, bmatB, data, k, m, lane_tile):
    b, _, n = data.shape
    return pl.pallas_call(
        _make_v8_kernel(k, m),
        grid=(b // 4, n // lane_tile),
        in_specs=[
            pl.BlockSpec((128, 128), lambda b, c: (0, 0)),
            pl.BlockSpec((128, 128), lambda b, c: (0, 0)),
            pl.BlockSpec((4, k, lane_tile), lambda b, c: (b, 0, c)),
        ],
        out_specs=pl.BlockSpec((4, m, lane_tile), lambda b, c: (b, 0, c)),
        out_shape=jax.ShapeDtypeStruct((b, m, n), jnp.uint8),
    )(bmatA, bmatB, data)


def _v4_matrices(bmat_np: np.ndarray, k: int, m: int):
    """bits row (b, s, i) = b*S*k + s*k + i; acc row (b', s, j) =
    b'*S*m + s*m + j; out row (s, j) = s*m + j."""
    bb = np.zeros((8 * S * m, 8 * S * k), np.int8)
    for bp in range(8):
        for b in range(8):
            for s in range(S):
                for j in range(m):
                    for i in range(k):
                        bb[bp * S * m + s * m + j, b * S * k + s * k + i] = (
                            bmat_np[j * 8 + bp, i * 8 + b]
                        )
    wb = np.zeros((S * m, 8 * S * m), np.int8)
    for bp in range(8):
        v = (1 << bp) if bp < 7 else -128
        for s in range(S):
            for j in range(m):
                wb[s * m + j, bp * S * m + s * m + j] = v
    return bb, wb


def _make_v4_kernel(k: int, m: int, pack: str):
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bmat_ref, wmat_ref, data_ref, out_ref):
        d = data_ref[:]  # [S, K, T'] uint8
        t = d.shape[2]
        flat = d.reshape(S * k, t)  # row s*k+i
        xi = pltpu.bitcast(flat, jnp.int32)  # [S*k/4, T']
        planes = []
        for b in range(8):
            pb = (xi >> jnp.int32(b)) & jnp.int32(0x01010101)
            planes.append(pltpu.bitcast(pb, jnp.int8))  # [S*k, T']
        bits = jnp.concatenate(planes, axis=0)  # [8*S*k, T']
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [8*S*m, T']
        if pack == "mm":
            acc8 = acc.astype(jnp.int8) & jnp.int8(1)
            packed = jax.lax.dot_general(
                wmat_ref[:], acc8,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [S*m, T']
        else:  # shift-or pack on full tiles
            sm = S * m
            packed = acc[0:sm] & jnp.int32(1)
            for b in range(1, 8):
                packed = packed | (
                    (acc[b * sm : (b + 1) * sm] & jnp.int32(1))
                    << jnp.int32(b)
                )
        out_ref[:] = packed.astype(jnp.uint8).reshape(S, m, t)

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "m", "lane_tile", "pack"))
def v4_encode(bmat_big, wmat_big, data, k, m, lane_tile, pack="mm"):
    b, _, n = data.shape
    return pl.pallas_call(
        _make_v4_kernel(k, m, pack),
        grid=(b // S, n // lane_tile),
        in_specs=[
            pl.BlockSpec(bmat_big.shape, lambda b, c: (0, 0)),
            pl.BlockSpec(wmat_big.shape, lambda b, c: (0, 0)),
            pl.BlockSpec((S, k, lane_tile), lambda b, c: (b, 0, c)),
        ],
        out_specs=pl.BlockSpec((S, m, lane_tile), lambda b, c: (b, 0, c)),
        out_shape=jax.ShapeDtypeStruct((b, m, n), jnp.uint8),
    )(bmat_big, wmat_big, data)


def main() -> None:
    variants = sys.argv[1:] or ["copy", "cur", "v9-65536", "v12-65536"]

    g = vandermonde_rs_matrix(K, M)
    bmat_np = gf_matrix_to_bitmatrix(g[K:, :])
    bmat_pm = jnp.asarray(
        pe._plane_major_bitmatrix(bmat_np, K, M).astype(np.int8)
    )
    wmat = jnp.asarray(_pack_weights(M))

    bb_np, wb_np = _v4_matrices(bmat_np, K, M)
    bmat_big4 = jnp.asarray(bb_np)
    wmat_big4 = jnp.asarray(wb_np)

    rng = np.random.default_rng(0)

    # correctness first, small shape
    small = jnp.asarray(rng.integers(0, 256, (8, K, 8192), np.uint8))
    ref = np.asarray(gf_encode_bitplane(jnp.asarray(bmat_np), small))
    for v in variants:
        if v.startswith("v3"):
            got = np.asarray(v3_encode(bmat_pm, wmat, small, K, M, 8192))
        elif v.startswith("v12"):
            b9 = _v9_matrices(bmat_np, K, M)
            got = np.asarray(v12_encode(jnp.asarray(b9), small, K, M, 4096))
        elif v.startswith("v11"):
            b11 = _v11_matrices(bmat_np, K, M)
            got = np.asarray(
                v11_encode(
                    jnp.asarray(b11), small, K, M, 4096, "p8" in v
                )
            )
        elif v.startswith("v9"):
            b9 = _v9_matrices(bmat_np, K, M)
            got = np.asarray(
                v9_encode(
                    jnp.asarray(b9), small, K, M, 4096, "i32" in v,
                    v.endswith("ds"),
                )
            )
        elif v.startswith("v8"):
            bA, bB = _v8_matrices(bmat_np, K, M)
            got = np.asarray(
                v8_encode(jnp.asarray(bA), jnp.asarray(bB), small, K, M, 4096)
            )
        elif v.startswith("v6") and "abl" not in v:
            sc = int(v[2])
            bb, wb = _v6_matrices(bmat_np, K, M, sc)
            got = np.asarray(
                v6_encode(
                    jnp.asarray(bb), jnp.asarray(wb), small, K, M, 4096, sc
                )
            )
        elif v.startswith("v5"):
            sc = int(v[2])  # v5{s}-{tile}
            bb, wb = _vS_matrices(bmat_np, K, M, sc)
            got = np.asarray(
                v5_encode(
                    jnp.asarray(bb), jnp.asarray(wb), small, K, M, 4096, sc
                )
            )
        elif v.startswith("v4"):
            pack = "mm" if "mm" in v else "so"
            got = np.asarray(
                v4_encode(bmat_big4, wmat_big4, small, K, M, 4096, pack)
            )
        else:
            continue
        ok = np.array_equal(ref, got)
        print(f"correctness {v}: {'OK' if ok else 'MISMATCH'}")
        if not ok:
            return

    data = jnp.asarray(
        rng.integers(0, 256, (BATCH, K, CHUNK)).astype(np.uint8)
    )

    applies = {}
    for v in variants:
        if v == "copy":
            applies[v] = lambda d: copy_probe(d, 65536)
        elif v == "cur":
            # whatever ships in ops/pallas_encode right now
            applies[v] = lambda d: pe.gf_encode_bitplane_pallas(bmat_np, d)
        elif v.startswith("v3"):
            t = int(v.split("-")[1])
            applies[v] = (lambda t: lambda d: v3_encode(bmat_pm, wmat, d, K, M, t))(t)
        elif v.startswith("v12"):
            t = int(v.split("-")[1])
            b9j = jnp.asarray(_v9_matrices(bmat_np, K, M))
            applies[v] = (
                lambda t, b9j: lambda d: v12_encode(b9j, d, K, M, t)
            )(t, b9j)
        elif v.startswith("v11"):
            t = int(v.split("-")[1])
            b11j = jnp.asarray(_v11_matrices(bmat_np, K, M))
            applies[v] = (
                lambda t, b11j, p8: lambda d: v11_encode(
                    b11j, d, K, M, t, p8
                )
            )(t, b11j, "p8" in v)
        elif v.startswith("v9"):
            # v9-<tile>[-i32][-ds]
            t = int(v.split("-")[1])
            i32 = "i32" in v
            ds = v.endswith("ds")
            b9j = jnp.asarray(_v9_matrices(bmat_np, K, M))
            applies[v] = (
                lambda t, b9j, i32, ds: lambda d: v9_encode(
                    b9j, d, K, M, t, i32, ds
                )
            )(t, b9j, i32, ds)
        elif v.startswith("v8"):
            t = int(v.split("-")[1])
            bA, bB = _v8_matrices(bmat_np, K, M)
            bAj, bBj = jnp.asarray(bA), jnp.asarray(bB)
            applies[v] = (
                lambda t, bAj, bBj: lambda d: v8_encode(bAj, bBj, d, K, M, t)
            )(t, bAj, bBj)
        elif v.startswith("v6"):
            # name: v6{s}-{tile} or v6{s}-{tile}-abl{planes|bits|mm}
            parts = v.split("-")
            sc = int(v[2])
            t = int(parts[1])
            abl = parts[2][3:] if len(parts) > 2 else ""
            bb, wb = _v6_matrices(bmat_np, K, M, sc)
            bbj, wbj = jnp.asarray(bb), jnp.asarray(wb)
            applies[v] = (
                lambda t, sc, bbj, wbj, abl: lambda d: v6_encode(
                    bbj, wbj, d, K, M, t, sc, abl
                )
            )(t, sc, bbj, wbj, abl)
        elif v.startswith("v5"):
            # name: v5{s}-{tile}
            sc = int(v[2])
            t = int(v.split("-")[1])
            bb, wb = _vS_matrices(bmat_np, K, M, sc)
            bbj, wbj = jnp.asarray(bb), jnp.asarray(wb)
            applies[v] = (
                lambda t, sc, bbj, wbj: lambda d: v5_encode(
                    bbj, wbj, d, K, M, t, sc
                )
            )(t, sc, bbj, wbj)
        elif v.startswith("v4"):
            # name: v4mm-4096 / v4so-4096
            pack = "mm" if "mm" in v else "so"
            t = int(v.split("-")[1])
            applies[v] = (
                lambda t, p: lambda d: v4_encode(
                    bmat_big4, wmat_big4, d, K, M, t, p
                )
            )(t, pack)

    for n in (N1, N2):
        _timed(_loop_perturb, data, n)
    pert = _per_iter(_loop_perturb, data)
    print(f"perturb-only: {pert*1e3:.3f} ms/iter")

    bytes_in = BATCH * K * CHUNK
    for name, apply in applies.items():
        try:
            loop = _loop(apply, M)
            for n in (N1, N2):
                _timed(loop, data, n)
            dt = max(_per_iter(loop, data) - pert, 1e-9)
            gbps = bytes_in / dt / 1e9
            traffic = gbps * (K + M) / K
            print(
                f"{name:10s}: {gbps:7.1f} GB/s data-in   "
                f"traffic {traffic:7.1f} GB/s  ({traffic/819:.0%} roofline)"
            )
        except Exception as e:
            print(f"{name:10s}: FAILED {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
