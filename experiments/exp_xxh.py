"""xxhash scan-layout experiment (round 4): the shipping kernels feed
lax.scan a [G, B, f*S] operand built by reshape+swapaxes — which can
materialize a transposed full-size copy through HBM (2x traffic).
Variant: fori_loop + dynamic_slice_in_dim on the ORIGINAL [B, L]
layout (no transpose). Same math, same unroll.

Usage: PYTHONPATH=/root/repo python exp_xxh.py
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import ceph_tpu.checksum.xxhash as xx
import ceph_tpu.checksum.u64 as u64
from bench import _hash_loop_gbps


def xxh32_slice_kernel(data, seed, *, block_bytes):
    p1, p2, p3, p4, p5 = (jnp.uint32(p) for p in xx._P32)
    n = block_bytes
    bsz = data.shape[0]
    seed = seed.astype(jnp.uint32)
    assert n >= 16 and n % 16 == 0
    nstripes = n // 16
    init = jnp.broadcast_to(
        jnp.stack([seed + p1 + p2, seed + p2, seed, seed - p1]),
        (bsz, 4),
    )
    f, main = xx._unroll_split(nstripes)

    def body(g, acc):
        group = jax.lax.dynamic_slice_in_dim(
            data, g * (f * 16), f * 16, axis=1
        )
        lanes = xx._le32(group.reshape(bsz, f, 4, 4))
        for j in range(f):
            acc = acc + lanes[:, j] * p2
            acc = xx._rotl32(acc, 13) * p1
        return acc

    acc = jax.lax.fori_loop(0, main // f, body, init)
    for s in range(main, nstripes):
        lanes = xx._le32(data[:, s * 16 : (s + 1) * 16].reshape(bsz, 4, 4))
        acc = acc + lanes * p2
        acc = xx._rotl32(acc, 13) * p1
    h = (
        xx._rotl32(acc[:, 0], 1)
        + xx._rotl32(acc[:, 1], 7)
        + xx._rotl32(acc[:, 2], 12)
        + xx._rotl32(acc[:, 3], 18)
    )
    h = h + jnp.uint32(n)
    h = h ^ (h >> 15)
    h = h * p2
    h = h ^ (h >> 13)
    h = h * p3
    return h ^ (h >> 16)


def xxh64_slice_kernel(data, *, block_bytes):
    p1, p2, p3, p4, p5 = (u64.from_const(p) for p in xx._P64)
    n = block_bytes
    bsz = data.shape[0]
    zero = (jnp.zeros((bsz,), jnp.uint32), jnp.zeros((bsz,), jnp.uint32))
    seed = zero
    assert n >= 32 and n % 32 == 0
    nstripes = n // 32
    init4 = [
        u64.add(seed, u64.add(p1, p2)),
        u64.add(seed, p2),
        seed,
        u64.add(seed, u64.from_const((-xx._P64[0]) & ((1 << 64) - 1))),
    ]
    init = (
        jnp.stack([a[0] for a in init4], axis=-1),
        jnp.stack([a[1] for a in init4], axis=-1),
    )
    f, main = xx._unroll_split(nstripes)

    def body(g, acc):
        group = jax.lax.dynamic_slice_in_dim(
            data, g * (f * 32), f * 32, axis=1
        )
        hi, lo = xx._le64_pair(group.reshape(bsz, f, 4, 8))
        for j in range(f):
            acc = xx._xxh64_round(acc, (hi[:, j], lo[:, j]))
        return acc

    acc = jax.lax.fori_loop(0, main // f, body, init)
    for s in range(main, nstripes):
        hi, lo = xx._le64_pair(data[:, s * 32 : (s + 1) * 32].reshape(bsz, 4, 8))
        acc = xx._xxh64_round(acc, (hi, lo))
    accs = [(acc[0][:, j], acc[1][:, j]) for j in range(4)]
    h = u64.add(
        u64.add(u64.rotl(accs[0], 1), u64.rotl(accs[1], 7)),
        u64.add(u64.rotl(accs[2], 12), u64.rotl(accs[3], 18)),
    )
    for j in range(4):
        h = u64.xor(h, xx._xxh64_round(zero, accs[j]))
        h = u64.add(u64.mul(h, p1), p4)
    h = u64.add(h, u64.from_const(n))
    h = u64.xor(h, u64.shr(h, 33))
    h = u64.mul(h, p2)
    h = u64.xor(h, u64.shr(h, 29))
    h = u64.mul(h, p3)
    return u64.xor(h, u64.shr(h, 32))


def main():
    rng = np.random.default_rng(3)
    blocks = jnp.asarray(
        rng.integers(0, 256, ((64 << 20) // 4096, 4096), np.uint8)
    )
    # correctness
    from ceph_tpu.checksum.reference import xxh32_ref, xxh64_ref

    small = np.asarray(rng.integers(0, 256, (3, 4096), np.uint8))
    j32 = jax.jit(lambda d: xxh32_slice_kernel(
        d, jnp.uint32(0), block_bytes=4096))
    j64 = jax.jit(lambda d: xxh64_slice_kernel(d, block_bytes=4096))
    g32 = np.asarray(j32(jnp.asarray(small)))
    g64 = j64(jnp.asarray(small))
    for i in range(3):
        assert int(g32[i]) == xxh32_ref(small[i].tobytes()), i
        have = (int(np.asarray(g64[0][i])) << 32) | int(np.asarray(g64[1][i]))
        assert have == xxh64_ref(small[i].tobytes()), i
    print("slice variants: correct", flush=True)

    def x32s(b):
        return j32(b)

    def x64s(b):
        h = j64(b)
        return (h[0] ^ h[1]).astype(jnp.uint32)

    def x32c(b):
        return xx.xxh32_device(b)

    def x64c(b):
        h = xx.xxh64_device(b)
        return (h[0] ^ h[1]).astype(jnp.uint32)

    for name, fn in (("cur32", x32c), ("slice32", x32s),
                     ("cur64", x64c), ("slice64", x64s),
                     ("cur64b", x64c), ("slice64b", x64s)):
        print(f"{name}: {_hash_loop_gbps(fn, blocks):.1f} GB/s", flush=True)


if __name__ == "__main__":
    main()
