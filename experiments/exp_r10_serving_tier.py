"""Round-10: pod-scale serving-tier sweep — the prepared tunnel run
for ISSUE 6's acceptance numbers.

The live path now pipelines client ops through the async objecter,
coalesces concurrent EC writes into per-tick device batches on each
OSD, packs sub-writes one frame per peer, and can serve ops over the
dispatch mesh / DCN tier. This script measures what each layer buys:

- ``cluster_vs_kernel_frac`` at qd ≫ 12 with THOUSANDS of zipfian
  objects, A/B coalesce on/off in the same session (the acceptance
  comparison: materially up with coalescing on);
- the qd ladder (8 → 64): does depth actually reach the wire now;
- the scaling row: GB/s and IOPS vs OSD count and vs chip count
  (mesh legs) — same rows the bench ``cluster`` phase emits, sized
  up for the tunnel session;
- the DCN hosts=3 leg with a mid-op host kill (VERDICT r5 #8):
  must report zero verify failures and op completion.

Run on the v5e tunnel:

    python experiments/exp_r10_serving_tier.py          # full sweep
    python experiments/exp_r10_serving_tier.py --quick  # CI-sized

The CPU fallback runs the same legs at toy sizes (correctness smoke;
absolute GB/s numbers mean nothing off-TPU)."""

import json
import sys
import time

sys.path.insert(0, ".")

QUICK = "--quick" in sys.argv


def _leg(tag, out, *, total_ops, qd, objects, coalesce=True,
         n_osds=6, use_mesh=False, mesh_devices=None,
         dcn_hosts=0, dcn_kill_at=0, seed=0xEC10):
    from ceph_tpu.loadgen import LoadCluster, WorkloadSpec, run_spec
    from ceph_tpu.loadgen.faults import FaultEvent, FaultSchedule
    from ceph_tpu.utils import config

    cluster = LoadCluster(
        n_osds=n_osds, k=4 if dcn_hosts == 0 else 3, m=2, pg_num=8,
        chunk_size=16384, use_mesh=use_mesh,
        mesh_devices=mesh_devices, dcn_hosts=dcn_hosts,
        dcn_data_timeout=5.0,
    )
    try:
        spec = WorkloadSpec(
            mix={"seq_write": 2, "rand_write": 1, "read": 3,
                 "reconstruct_read": 1, "rmw_overwrite": 1},
            object_size=256 * 1024, max_objects=objects,
            queue_depth=qd, total_ops=total_ops,
            warmup_ops=max(total_ops // 10, 8),
            popularity="zipfian", seed=seed,
        )
        schedule = None
        if dcn_kill_at:
            schedule = FaultSchedule(
                [FaultEvent(at_op=dcn_kill_at, action="dcn_kill")]
            )
        t0 = time.monotonic()
        with config.override(osd_op_coalescing=coalesce):
            report = run_spec(cluster, spec, schedule)
        coal = sum(
            d.coalesce_pc.get("op_coalesced")
            for d in cluster.daemons.values()
        )
    finally:
        cluster.shutdown()
    out[tag] = {
        "gbps": report["gbps"], "iops": report["iops"],
        "errors": report["errors"],
        "verify_failures": report["verify_failures"],
        "op_coalesced": coal,
        "wall_s": round(time.monotonic() - t0, 2),
    }
    print(f"  {tag}: {out[tag]}", flush=True)
    return report


def main() -> None:
    from ceph_tpu.utils import honor_platform_env

    honor_platform_env()
    import jax

    ops = 80 if QUICK else 2400
    objects = 32 if QUICK else 2048  # tunnel: thousands, zipfian
    out: dict = {"platform": jax.devices()[0].platform,
                 "ops": ops, "objects": objects}

    print("== A/B: coalesce on/off at qd 32 ==", flush=True)
    _leg("qd32_coalesce_on", out, total_ops=ops, qd=32,
         objects=objects, coalesce=True)
    _leg("qd32_coalesce_off", out, total_ops=ops, qd=32,
         objects=objects, coalesce=False, seed=0xEC11)
    on, off = out["qd32_coalesce_on"], out["qd32_coalesce_off"]
    if off["gbps"]:
        out["coalesce_speedup"] = round(on["gbps"] / off["gbps"], 3)

    print("== qd ladder ==", flush=True)
    for qd in (8, 16, 32, 64):
        _leg(f"qd{qd}", out, total_ops=ops, qd=qd, objects=objects,
             seed=0xEC20 + qd)

    print("== OSD scaling ==", flush=True)
    for n in (6, 9, 12):
        _leg(f"osd{n}", out, total_ops=max(ops // 2, 40), qd=32,
             objects=objects, n_osds=n, seed=0xEC30 + n)

    print("== chip scaling (mesh) ==", flush=True)
    n_dev = len(jax.devices())
    for chips in sorted({c for c in (1, 2, 4, n_dev) if c <= n_dev}):
        _leg(f"chips{chips}", out, total_ops=max(ops // 2, 40), qd=32,
             objects=objects, use_mesh=chips > 1,
             mesh_devices=chips if chips > 1 else None,
             seed=0xEC40 + chips)

    print("== DCN hosts=3, mid-op host kill (VERDICT r5 #8) ==",
          flush=True)
    rep = _leg("dcn3_host_kill", out, total_ops=max(ops // 4, 24),
               qd=8, objects=min(objects, 64), dcn_hosts=3,
               dcn_kill_at=max(ops // 12, 8), seed=0xEC50)
    out["dcn3_zero_verify_failures"] = rep["verify_failures"] == 0

    # acceptance summary
    out["accept_coalesce_up"] = bool(
        off["gbps"] and on["gbps"] > off["gbps"]
    )
    print(json.dumps(out, indent=2, sort_keys=True))


if __name__ == "__main__":
    main()
