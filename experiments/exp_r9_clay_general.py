"""Round-9: CLAY general-d plane-blocked repair sweep — the prepared
tunnel run for ISSUE 5's acceptance numbers.

The production path (codecs/clay.py _repair_kernels +
ops/clay_kernels.py) now serves ANY ``k <= d <= k+m-1`` and any
``sub_chunk_no * sc`` through 2D lane-blocked Pallas refs.  This
script measures, per geometry x chunk size:

- helper-read GB/s through the kernel path (the bench
  ``clay_repair_gbps`` methodology: serially-dependent feedback loop,
  diff-of-minima timing);
- the same with ``ec_clay_kernels=false`` (the XLA fast/itemized
  comparators the kernels replace);
- ``time_vs_naive`` against a 1-row RS reconstruct over k full
  chunks (decode1) measured inline — the < 1.0 acceptance target
  (helper-read >= ~130 GB/s at the 0.344x byte ratio break-even);
- the aloof path's rate vs the aloof-free rate (target: within 20%).

Run on the v5e tunnel:

    python experiments/exp_r9_clay_general.py          # full sweep
    python experiments/exp_r9_clay_general.py --quick  # one config

Off-TPU the kernels run in interpreter mode on the smallest config
(correctness smoke only; the timings mean nothing there).
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np
import jax
import jax.numpy as jnp

from ceph_tpu.codecs.registry import registry
from ceph_tpu.gf import (
    decode_matrix,
    gf_matrix_to_bitmatrix,
    vandermonde_rs_matrix,
)
from ceph_tpu.ops import pallas_encode as pe
from ceph_tpu.utils import config


def timed(fn, *args):
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    return time.perf_counter() - t0


def loop_stats(loop, data, target=0.45, reps=3):
    base = min(timed(loop, data, 1) for _ in range(2))
    n2 = 60
    while n2 < 40000:
        if timed(loop, data, n2) - base >= target:
            break
        n2 *= 2
    n1 = max(1, n2 // 10)
    t1 = min(timed(loop, data, n1) for _ in range(reps))
    t2 = min(timed(loop, data, n2) for _ in range(reps))
    return (t2 - t1) / (n2 - n1)


def device_rand(shape, seed):
    key = jax.random.PRNGKey(seed)
    return jax.random.randint(
        key, shape, 0, 256, dtype=jnp.int32
    ).astype(jnp.uint8)


def repair_loop(codec, lost, keys):
    @jax.jit
    def loop(arrs, iters):
        def body(i, carry):
            arrs, acc = carry
            out = codec.repair({lost}, dict(zip(keys, arrs)))[lost]
            fold = jax.lax.dynamic_slice(out, (0, 0), (1, 128))
            first = jax.lax.dynamic_update_slice(
                arrs[0], fold ^ jnp.uint8(i + 1), (0, 0)
            )
            return (first,) + arrs[1:], acc + jnp.sum(
                fold, dtype=jnp.uint32
            )

        _, acc = jax.lax.fori_loop(0, iters, body, (arrs, jnp.uint32(0)))
        return acc

    return loop


def decode1_loop(k, m, chunk, stripes, seed=5):
    """1-row RS reconstruct over k full chunks — the naive repair
    comparator, measured inline so every sweep row is self-contained."""
    g = vandermonde_rs_matrix(k, m)
    present = [i for i in range(k + m) if i != 4][: k]
    dmat = decode_matrix(g, k, present)
    bmat = gf_matrix_to_bitmatrix(dmat[4:5, :])
    data = device_rand((stripes, k, chunk), seed)

    def apply(d):
        return pe.gf_encode_bitplane_pallas(bmat, d)

    @jax.jit
    def loop(d0, iters):
        def body(i, carry):
            d, acc = carry
            out = apply(d)
            fold = jax.lax.dynamic_slice(
                out, (0, 0, 0), (1, 1, 128)
            )
            d = jax.lax.dynamic_update_slice(
                d, fold ^ jnp.uint8(i + 1), (0, 0, 0)
            )
            return d, acc ^ fold.reshape(-1)[0]

        _, acc = jax.lax.fori_loop(0, iters, body, (d0, jnp.uint8(0)))
        return acc

    return loop, data, stripes * k * chunk


def sweep_row(kk, m, d, chunk_kib, stripes, naive_per_byte):
    codec = registry.factory(
        "clay", {"k": str(kk), "m": str(m), "d": str(d)}
    )
    n = kk + m
    sub = codec.get_sub_chunk_count()
    chunk = codec.get_chunk_size(kk * chunk_kib * 1024)
    sc = chunk // sub
    lost = kk + 1
    plan = codec.minimum_to_decode({lost}, set(range(n)) - {lost})
    helper, read = {}, 0
    for hseed, (node, ranges) in enumerate(sorted(plan.items())):
        nbytes = sum(c for _i, c in ranges) * sc
        read += stripes * nbytes
        helper[node] = device_rand((stripes, nbytes), 100 + hseed)
    keys = sorted(helper)
    arrs = tuple(helper[kk2] for kk2 in keys)
    loop = repair_loop(codec, lost, keys)
    per = loop_stats(loop, arrs)
    with config.override(ec_clay_kernels=False):
        loop_xla = repair_loop(codec, lost, keys)
        per_xla = loop_stats(loop_xla, arrs)
    naive_s = naive_per_byte * kk * chunk * stripes
    row = {
        "geom": f"({kk},{m},d={d})",
        "chunk_kib": chunk // 1024,
        "sub_chunk_no": sub,
        "read_frac": round(read / (kk * chunk * stripes), 3),
        "kernel_gbps": round(read / per / 1e9, 2),
        "xla_gbps": round(read / per_xla / 1e9, 2),
        "kernel_vs_xla": round(per_xla / per, 2),
        "time_vs_naive": round(per / naive_s, 2),
    }
    print(row, flush=True)
    return row


def main():
    quick = "--quick" in sys.argv
    on_tpu = pe.on_tpu()
    if not on_tpu:
        print("# off-TPU: interpreter-mode correctness smoke only")
        sweep_row(4, 2, 5, 1, 8, naive_per_byte=1e-9)
        return
    # naive comparator at the flagship shape (64 KiB and 1 MiB chunks)
    rows = []
    for chunk_kib, stripes in ((64, 256), (1024, 16)):
        loop, data, nbytes = decode1_loop(8, 4, chunk_kib * 1024, stripes)
        naive_per_byte = loop_stats(loop, data) / nbytes
        print(
            {"decode1_gbps": round(1 / naive_per_byte / 1e9, 2),
             "chunk_kib": chunk_kib},
            flush=True,
        )
        geoms = [(8, 4, 11)] if quick else [
            (8, 4, 11),   # aloof-free flagship
            (8, 4, 10),   # one aloof (q=3)
            (8, 4, 9),    # two aloof (q=2)
            (6, 3, 7),    # aloof + shortened (nu=1)
        ]
        for kk, m, d in geoms:
            try:
                rows.append(sweep_row(
                    kk, m, d, chunk_kib, stripes, naive_per_byte
                ))
            except Exception as e:
                print({"geom": f"({kk},{m},d={d})",
                       "error": f"{type(e).__name__}: {e}"[:200]},
                      flush=True)
        if quick:
            break
    # acceptance summary
    by_geom = {r["geom"]: r for r in rows if r["chunk_kib"] >= 512}
    flag = by_geom.get("(8,4,d=11)")
    alo = by_geom.get("(8,4,d=10)")
    if flag:
        print({
            "accept_time_vs_naive_lt_1": flag["time_vs_naive"] < 1.0,
            "accept_aloof_within_20pct": (
                alo is not None
                and alo["kernel_gbps"] >= 0.8 * flag["kernel_gbps"]
            ),
        }, flush=True)


if __name__ == "__main__":
    main()
