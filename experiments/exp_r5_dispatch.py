"""Round-5: where does the codec dispatch path lose bandwidth?

Bare schedule kernel: ~550-620 GB/s at [32, 28, 32768]. Full
encode_chunks through the same kernel: ~99. Variants peel the layers:

  v0  bare kernel, pre-stacked pre-packetized input
  v1  + input stack-of-slices (the _stack_data copy)
  v2  + output depacketize/slice/restack (the bench's consumer shape)
  v3  the real codec.encode_chunks (all of the above + dispatch logic)
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from ceph_tpu.codecs import registry
from ceph_tpu.ops import xor_schedule


def loop_gbps(apply, data, nbytes, n1=100, n2=2100, reps=5):
    @jax.jit
    def loop(d0, iters):
        def body(i, carry):
            d, acc = carry
            patch = (
                jax.lax.dynamic_slice(d, (0, 0, 0), (1, 1, 128))
                ^ jnp.uint8(i + 1)
            )
            d = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
            out = apply(d)
            fold = jax.lax.dynamic_slice(out, (0, 0, 0), (1, 1, 128))[0, 0, 0]
            return d, acc ^ fold

        _, acc = jax.lax.fori_loop(0, iters, body, (d0, jnp.uint8(0)))
        return acc

    def timed(iters):
        t0 = time.perf_counter()
        np.asarray(loop(data, iters))
        return time.perf_counter() - t0

    for t in (n1, n2):
        timed(t)
    t1 = min(timed(n1) for _ in range(reps))
    t2 = min(timed(n2) for _ in range(reps))
    return nbytes / ((t2 - t1) / (n2 - n1)) / 1e9


def main():
    rng = np.random.default_rng(11)
    codec = registry.factory(
        "jerasure", {"technique": "liberation", "k": "4", "m": "2", "w": "7"}
    )
    k, w = 4, 7
    chunk = 7 * 32768
    p = chunk // w
    kw = k * w
    rows = xor_schedule.schedule_rows(codec.coding_bitmatrix)
    nbytes = 32 * k * chunk

    # v0: bare kernel
    packets = jnp.asarray(rng.integers(0, 256, (32, kw, p), np.uint8))
    g = loop_gbps(
        lambda d: xor_schedule.xor_schedule_apply(rows, d), packets, nbytes
    )
    print(f"v0 bare kernel:            {g:.1f} GB/s", flush=True)

    full = jnp.asarray(rng.integers(0, 256, (32, k, chunk), np.uint8))

    # v1: + stack of slices -> packetize
    def v1(d):
        stacked = jnp.stack([d[:, i, :] for i in range(k)], axis=-2)
        pk = stacked.reshape(32, kw, p)
        return xor_schedule.xor_schedule_apply(rows, pk)

    print(f"v1 + input stack:          {loop_gbps(v1, full, nbytes):.1f} GB/s",
          flush=True)

    # v1b: reshape WITHOUT the stack (d already [B, k, chunk])
    def v1b(d):
        pk = d.reshape(32, kw, p)
        return xor_schedule.xor_schedule_apply(rows, pk)

    print(f"v1b reshape only:          {loop_gbps(v1b, full, nbytes):.1f} GB/s",
          flush=True)

    # v2: + output depacketize/slice/restack
    def v2(d):
        stacked = jnp.stack([d[:, i, :] for i in range(k)], axis=-2)
        pk = stacked.reshape(32, kw, p)
        out = xor_schedule.xor_schedule_apply(rows, pk)
        chunks = out.reshape(32, 2, chunk)
        parts = {k + i: chunks[..., i, :] for i in range(2)}
        return jnp.stack([parts[j] for j in sorted(parts)], axis=1)

    print(f"v2 + output restack:       {loop_gbps(v2, full, nbytes):.1f} GB/s",
          flush=True)

    # v3: real codec path
    def v3(d):
        parity = codec.encode_chunks({i: d[:, i, :] for i in range(k)})
        return jnp.stack([parity[j] for j in sorted(parity)], axis=1)

    print(f"v3 codec.encode_chunks:    {loop_gbps(v3, full, nbytes):.1f} GB/s",
          flush=True)


if __name__ == "__main__":
    main()
