"""Round-20: Messenger v2 transport/codec grid — the prepared tunnel
run for ISSUE 20's acceptance numbers.

The messenger grew a native (C) clear-frame codec behind
``msgr_native_codec``, a shared-memory ring lane for co-located peers
behind ``msgr_transport=shm_ring``, and the OSD op worker split into
per-PG-hash shards behind ``osd_op_num_shards``. This script measures
what the tier buys, as within-run A/Bs (same seed, same process, so
tunnel drift cancels):

- the transport x codec grid: the same mixed workload over
  {tcp, shm_ring} x {python, native} frame codecs — gbps / iops /
  p99 per leg plus ``vs_kernel_frac`` (cluster throughput as a
  fraction of the raw encode kernel rate: how much of the device's
  rate the cluster plumbing delivers end-to-end);
- trace-attributed critical paths on the two corner legs (tcp+python
  vs shm_ring+native): per-lane self-time from the span trees —
  the wire/queue share must shrink when the codec goes native and
  the frames stop crossing a socket;
- the head-of-line rows: flood x kill tenant-A latency spread at
  1 vs 4 op shards, plus the deterministic parked-shard sibling
  probe (the single-worker wedge, measured directly).

Run on the v5e tunnel:

    python experiments/exp_r20_transport.py                # full
    python experiments/exp_r20_transport.py --quick        # CI-sized
    python experiments/exp_r20_transport.py --enc-gbps 57  # reuse
        bench.py's kernel headline as the vs-kernel denominator

The CPU fallback runs the same legs at toy sizes (correctness smoke;
absolute rates mean nothing off-TPU)."""

import json
import sys
import time

sys.path.insert(0, ".")

QUICK = "--quick" in sys.argv


def _enc_gbps_arg():
    for i, a in enumerate(sys.argv):
        if a == "--enc-gbps" and i + 1 < len(sys.argv):
            return float(sys.argv[i + 1])
        if a.startswith("--enc-gbps="):
            return float(a.split("=", 1)[1])
    return None


def _kernel_gbps(k=4, m=2, chunk=16384, batch=8, iters=10):
    """Encode-kernel rate through the codec front door (includes
    host<->device staging — a conservative denominator; pass
    ``--enc-gbps`` with bench.py's pure device-loop headline for the
    strict one)."""
    import numpy as np

    from ceph_tpu.codecs import create_codec

    codec = create_codec(
        "jerasure", k=str(k), m=str(m), technique="reed_sol_van",
    )
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, batch * k * chunk, np.uint8).tobytes()
    codec.encode(data)  # warm + compile
    t0 = time.perf_counter()
    for _ in range(iters):
        codec.encode(data)
    dt = time.perf_counter() - t0
    return len(data) * iters / dt / 1e9


def _lane_self_ms(cap):
    """Sum critical-path self time by lane across the captured
    traces: the 'where does the wall time live' attribution."""
    lanes: dict = {}
    for cp in cap.get("critical_paths", []):
        for st in cp.get("stages", []):
            lanes[st["lane"]] = lanes.get(st["lane"], 0.0) + st["self_s"]
    return {k: round(v * 1e3, 3) for k, v in sorted(lanes.items())}


def _leg(tag, out, *, transport, native_codec, total_ops, qd, objects,
         object_size, enc_gbps=None, trace=False, seed=0xEC20):
    """One grid leg: the standard mixed workload with the messenger
    lane and frame codec pinned for the cluster's whole lifetime."""
    from ceph_tpu.loadgen import LoadCluster, WorkloadSpec, run_spec
    from ceph_tpu.msg import shm_ring
    from ceph_tpu.utils import config
    from ceph_tpu.utils.trace import tracer

    shm_ring.reset_stats()
    with config.override(msgr_transport=transport,
                         msgr_native_codec=native_codec):
        cluster = LoadCluster(
            n_osds=6, k=4, m=2, pg_num=8, chunk_size=16384,
        )
        try:
            if trace:
                tracer.clear()
            spec = WorkloadSpec(
                mix={"seq_write": 2, "rand_write": 1, "read": 3,
                     "rmw_overwrite": 1},
                object_size=object_size, max_objects=objects,
                queue_depth=qd, total_ops=total_ops,
                warmup_ops=max(total_ops // 10, 8),
                popularity="zipfian", seed=seed,
            )
            t0 = time.monotonic()
            report = run_spec(cluster, spec, None)
            row = {
                "gbps": report["gbps"],
                "iops": report["iops"],
                "p99_ms": report.get("lat_p99_ms"),
                "errors": report["errors"],
                "verify_failures": report["verify_failures"],
                "wall_s": round(time.monotonic() - t0, 2),
            }
            if transport == "shm_ring":
                snap = shm_ring.snapshot()
                row["shm_chunks"] = snap["chunks"]
                row["shm_bytes"] = snap["bytes"]
            if enc_gbps:
                row["vs_kernel_frac"] = round(
                    report["gbps"] / enc_gbps, 6
                )
            if trace:
                from ceph_tpu.utils.trace_assembly import capture_traces

                cap = capture_traces(limit=8)
                row["trace_lane_self_ms"] = _lane_self_ms(cap)
        finally:
            cluster.shutdown()
    out[tag] = row
    print(f"  {tag}: {row}", flush=True)
    return row


def main() -> None:
    from ceph_tpu.utils import honor_platform_env

    honor_platform_env()
    import jax

    ops = 48 if QUICK else 640
    objects = 24 if QUICK else 256
    qd = 8 if QUICK else 32
    osize = 16 * 1024 if QUICK else 256 * 1024
    out: dict = {"platform": jax.devices()[0].platform,
                 "ops": ops, "objects": objects, "qd": qd}

    enc_gbps = _enc_gbps_arg()
    if enc_gbps is None:
        enc_gbps = round(_kernel_gbps(), 3)
        out["enc_gbps_source"] = "in-run codec.encode loop"
    else:
        out["enc_gbps_source"] = "--enc-gbps (bench.py headline)"
    out["enc_gbps"] = enc_gbps

    print("== transport x codec grid ==", flush=True)
    for tag, transport, native, trace in (
        ("tcp_py", "tcp", False, True),
        ("tcp_native", "tcp", True, False),
        ("shm_py", "shm_ring", False, False),
        ("shm_native", "shm_ring", True, True),
    ):
        _leg(tag, out, transport=transport, native_codec=native,
             total_ops=ops, qd=qd, objects=objects, object_size=osize,
             enc_gbps=enc_gbps, trace=trace, seed=0xEC20)
    if out["tcp_py"]["gbps"]:
        out["frame_codec_speedup"] = round(
            out["tcp_native"]["gbps"] / out["tcp_py"]["gbps"], 4
        )
    if out["tcp_native"]["gbps"]:
        out["shm_ring_speedup"] = round(
            out["shm_native"]["gbps"] / out["tcp_native"]["gbps"], 4
        )
    out["accept_shm_lane_used"] = bool(
        out["shm_native"].get("shm_chunks", 0) > 0
    )
    # wire/queue self-time across the corner legs: the gap stages on
    # the critical path (client close -> primary pickup, dispatch ->
    # sub-write) are where the codec + socket time lives
    wq0 = out["tcp_py"].get("trace_lane_self_ms", {}).get("wire/queue")
    wq1 = out["shm_native"].get(
        "trace_lane_self_ms", {}
    ).get("wire/queue")
    if wq0 and wq1:
        out["wire_queue_self_frac"] = round(wq1 / wq0, 4)

    print("== flood x kill shard ladder (1 vs 4 op shards) ==",
          flush=True)
    from ceph_tpu.loadgen.bench_phase import hol_probe_ms, qos_leg
    from ceph_tpu.utils import config

    for n in (1, 4):
        with config.override(osd_op_num_shards=n):
            rep = qos_leg(ops, qd, objects, flood=True, faults=True,
                          seed=0xEC20)
        a = rep.get("tenants", {}).get("tenantA", {})
        row = {pct: a.get(f"lat_{pct}_ms")
               for pct in ("p50", "p95", "p99")}
        row["verify_failures"] = rep.get("verify_failures")
        out[f"shards{n}_storm"] = row
        print(f"  shards{n}_storm: {row}", flush=True)

    print("== deterministic head-of-line probe ==", flush=True)
    h1 = hol_probe_ms(1)
    h4 = hol_probe_ms(4)
    out["hol_probe_shards1_ms"] = h1
    out["hol_probe_shards4_ms"] = h4
    if h1 > 0 and h4 > 0:
        out["hol_probe_frac"] = round(h4 / h1, 4)
        # the parked sibling must clear in a small fraction of the
        # park window once the worker is sharded
        out["accept_hol_removed"] = bool(h4 / h1 < 0.5)

    print(json.dumps(out, indent=2, sort_keys=True))


if __name__ == "__main__":
    main()
