"""Pack-stage experiment (round 4): the v3 kernel's int32->int8 astype
narrows the FULL accumulator (8SR rows) before the bitcast-nibble
merge — the playbook's "biggest remaining VPU cost". Variant: fold the
8 bit-plane rows of each output byte into ONE int32 row first
(8 and/shift/or ops on row slices), then narrow [SR, T] — 1/8 the
relayout traffic.

MEASURED DEAD END (v5e, same run): rowfold 43-48 GB/s vs 410 for the
shipped nibble pack — the per-b row slices of the [SR, 8, T] reshape
lower to strided sublane gathers that cost far more than the astype
they avoid. Keep the bitcast-nibble pack. (Running this experiment
also exposed the _v3_matrix_cached device-array tracer leak, now
fixed + regression-tested in tests/test_pallas.py.)

Usage: PYTHONPATH=/root/repo python exp_pack.py [k m]
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ceph_tpu.gf import gf_matrix_to_bitmatrix, vandermonde_rs_matrix
from ceph_tpu.ops import pallas_encode as pe
from ceph_tpu.ops.bitplane import gf_encode_bitplane
from exp_highk import BATCH, CHUNK, _gbps


def _rowfold_matrix(bitmatrix: np.ndarray, c: int, r: int, s: int, pad: int):
    """Stationary matrix for the row-fold pack variant: acc row
    = si*(8*r) + j*8 + bp (bit-plane-minor PER OUTPUT BYTE, so the
    fold combines 8 adjacent rows)."""
    f = s * c + pad
    mat = np.zeros((8 * s * r, 8 * f), np.int8)
    for si in range(s):
        for j in range(r):
            for bp in range(8):
                row = si * (8 * r) + j * 8 + bp
                for b in range(8):
                    for i in range(c):
                        mat[row, b * f + si * c + i] = bitmatrix[
                            j * 8 + bp, i * 8 + b
                        ]
    return mat


def _make_kernel(c, r, s, pad):
    def kernel(bmat_ref, data_ref, out_ref):
        d = data_ref[:]
        t = d.shape[2]
        flat = d.reshape(s * c, t)
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad, t), jnp.uint8)], axis=0
            )
        bits = pe.unpack_bitplanes(flat, False)
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [8SR, T], rows (si, j, bp)
        sr = s * r
        # row-fold: byte = sum_b (acc[8x+b] & 1) << b — stays int32,
        # narrows only the [SR, T] result
        folded = acc.reshape(sr, 8, t)
        out = jnp.zeros((sr, t), jnp.int32)
        for b in range(8):
            out = out | ((folded[:, b, :] & jnp.int32(1)) << jnp.int32(b))
        out_ref[:] = out.astype(jnp.uint8).reshape(s, r, t)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("c", "r", "s", "pad", "tile")
)
def _apply(bmat_big, data, c, r, s, pad, tile):
    batch, _, n = data.shape
    return pl.pallas_call(
        _make_kernel(c, r, s, pad),
        grid=(batch // s, n // tile),
        in_specs=[
            pl.BlockSpec(bmat_big.shape, lambda b, ch: (0, 0)),
            pl.BlockSpec((s, c, tile), lambda b, ch: (b, 0, ch)),
        ],
        out_specs=pl.BlockSpec((s, r, tile), lambda b, ch: (b, 0, ch)),
        out_shape=jax.ShapeDtypeStruct((batch, r, n), jnp.uint8),
    )(bmat_big, data)


def main():
    k = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    m = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    g = vandermonde_rs_matrix(k, m)
    bm = gf_matrix_to_bitmatrix(g[k:, :])
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (BATCH, k, CHUNK), np.uint8))
    small = jnp.asarray(rng.integers(0, 256, (8, k, 8192), np.uint8))
    ref = np.asarray(gf_encode_bitplane(jnp.asarray(bm), small))

    s, pad = pe._pick_stripes(k, BATCH)
    big = jnp.asarray(_rowfold_matrix(bm, k, m, s, pad))
    got = np.asarray(_apply(big, small, k, m, s, pad, 2048))
    if not np.array_equal(got, ref):
        print(f"rowfold s{s} pad{pad}: WRONG"); return
    for tile in (65536, 32768):
        gb = _gbps(lambda d: _apply(big, d, k, m, s, pad, tile), data, k)
        print(f"rowfold s{s} F={s*k+pad} tile={tile//1024}k: {gb:.1f} GB/s",
              flush=True)
    print(f"shipped: {_gbps(lambda d: pe.gf_encode_bitplane_pallas(bm, d), data, k):.1f} GB/s",
          flush=True)
    print(f"shipped rep2: {_gbps(lambda d: pe.gf_encode_bitplane_pallas(bm, d), data, k):.1f} GB/s",
          flush=True)


if __name__ == "__main__":
    main()
