"""Round-2 high-k sweep: confirm the s/pad rule across k (see exp_highk.py)."""
from __future__ import annotations

import sys

import jax.numpy as jnp
import numpy as np

from ceph_tpu.gf import gf_matrix_to_bitmatrix
from ceph_tpu.gf.matrices import cauchy_good_matrix
from ceph_tpu.ops import pallas_encode as pe
from ceph_tpu.ops.bitplane import gf_encode_bitplane
from exp_highk import BATCH, CHUNK, _gbps, variant


def run(k, m, cands):
    g = cauchy_good_matrix(k, m)
    bmat_np = gf_matrix_to_bitmatrix(g[k:, :])
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (BATCH, k, CHUNK), np.uint8))
    small = jnp.asarray(rng.integers(0, 256, (8, k, 8192), np.uint8))
    ref = np.asarray(gf_encode_bitplane(jnp.asarray(bmat_np), small))
    print(
        f"k={k} m={m} cur="
        f"{_gbps(lambda d: pe.gf_encode_bitplane_pallas(bmat_np, d), data, k):.1f}",
        flush=True,
    )
    for s, pad, tile in cands:
        f = s * k + pad
        name = f"  s{s} F={f} tile={tile//1024}k"
        try:
            got = np.asarray(variant(bmat_np, k, m, s, pad, 2048, False)(small))
            if not np.array_equal(got, ref):
                print(f"{name}: WRONG", flush=True)
                continue
            gb = _gbps(variant(bmat_np, k, m, s, pad, tile, False), data, k)
            print(f"{name}: {gb:.1f} GB/s", flush=True)
        except Exception as e:
            print(f"{name}: fail {type(e).__name__} {str(e)[:60]}", flush=True)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "21"
    if which == "21":
        run(21, 4, [
            (1, 3, 32768), (1, 3, 65536),      # F=24
            (1, 11, 32768),                     # F=32
            (2, 6, 32768), (2, 6, 16384),       # F=48
            (2, 2, 32768),                      # F=44
        ])
    elif which == "16":
        run(16, 4, [
            (1, 0, 32768), (1, 0, 65536),       # F=16
            (1, 8, 32768),                      # F=24
            (2, 0, 32768), (2, 0, 65536),       # F=32
            (2, 8, 32768),                      # F=40
        ])
    elif which == "32":
        run(32, 3, [
            (1, 0, 32768), (1, 0, 16384),       # F=32
            (1, 8, 32768),                      # F=40
            (1, 16, 32768),                     # F=48
            (2, 0, 16384), (2, 0, 32768),       # F=64
        ])
    elif which == "12":
        # k=12: s2 F=24 pad0 — the sweet spot exactly
        run(12, 4, [(2, 0, 32768), (2, 0, 65536), (1, 0, 32768), (1, 4, 32768)])
    elif which == "8":
        # flagship: does s4/F=32 beat the shipping s2/F=16?
        run(8, 4, [
            (2, 0, 65536), (2, 0, 32768),       # F=16 (shipping)
            (4, 0, 32768), (4, 0, 65536),       # F=32 full-useful
            (2, 8, 32768),                       # F=24
            (1, 24, 32768),                      # F=32 pad-heavy
        ])
    elif which == "16b":
        run(16, 4, [
            (1, 0, 65536), (1, 0, 32768),        # F=16
            (2, 0, 32768), (2, 0, 65536),        # F=32
            (1, 8, 32768),                        # F=24
        ])
    elif which == "32b":
        run(32, 3, [
            (1, 0, 32768), (1, 0, 65536), (1, 0, 16384),  # F=32
        ])
    elif which == "10b":
        run(10, 4, [
            (1, 6, 65536), (1, 6, 32768),         # F=16 (rule candidate)
            (1, 2, 65536),                         # F=12
            (2, 4, 32768), (2, 4, 65536),          # F=24 (prev winner)
        ])
    elif which == "12b":
        run(12, 4, [(1, 4, 65536), (2, 0, 32768), (2, 0, 65536)])
    elif which == "28":
        # liberation k=4 w=7 packet shape: c = 28
        run(28, 8, [(1, 4, 65536), (1, 4, 32768), (1, 0, 65536)])


if __name__ == "__main__":
    main()
