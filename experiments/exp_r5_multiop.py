"""Round-5: multi-operand schedule kernel — k separate [B, chunk]
shard operands, m separate [B, chunk] parity results, packet indexing
as in-kernel lane slices. No stack, no packetize reshape: the relayout
copies that cost the single-operand path 5x (exp_r5_dispatch.py:
v0 814 vs v1b 168 GB/s) never happen.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ceph_tpu.codecs import registry
from ceph_tpu.ops import xor_schedule


def make_multiop(sel_rows, k, w, chunk, sb):
    m = len(sel_rows) // w
    p = chunk // w

    def kernel(*refs):
        ins, outs = refs[:k], refs[k:]

        def packet(j):
            ci, pi = divmod(j, w)
            return ins[ci][:, pi * p : (pi + 1) * p]

        for q, sel in enumerate(sel_rows):
            if sel:
                acc = packet(sel[0])
                for j in sel[1:]:
                    acc = acc ^ packet(j)
            else:
                acc = jnp.zeros((sb, p), jnp.uint8)
            qc, qp = divmod(q, w)
            outs[qc][:, qp * p : (qp + 1) * p] = acc

    @jax.jit
    def apply(*shards):
        b = shards[0].shape[0]
        return pl.pallas_call(
            kernel,
            grid=(b // sb,),
            in_specs=[
                pl.BlockSpec((sb, chunk), lambda i: (i, 0))
                for _ in range(k)
            ],
            out_specs=[
                pl.BlockSpec((sb, chunk), lambda i: (i, 0))
                for _ in range(m)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, chunk), jnp.uint8)
                for _ in range(m)
            ],
        )(*shards)

    return apply


def loop_gbps(apply, shards, nbytes, n1=100, n2=2100, reps=5):
    @jax.jit
    def loop(arrs, iters):
        def body(i, carry):
            arrs, acc = carry
            first = arrs[0]
            patch = (
                jax.lax.dynamic_slice(first, (0, 0), (1, 128))
                ^ jnp.uint8(i + 1)
            )
            arrs = (
                jax.lax.dynamic_update_slice(first, patch, (0, 0)),
            ) + arrs[1:]
            outs = apply(*arrs)
            fold = outs[0][0, 0] ^ outs[1][0, 1]
            return arrs, acc ^ fold

        _, acc = jax.lax.fori_loop(0, iters, body, (arrs, jnp.uint8(0)))
        return acc

    def timed(iters):
        t0 = time.perf_counter()
        np.asarray(loop(shards, iters))
        return time.perf_counter() - t0

    for t in (n1, n2):
        timed(t)
    t1 = min(timed(n1) for _ in range(reps))
    t2 = min(timed(n2) for _ in range(reps))
    return nbytes / ((t2 - t1) / (n2 - n1)) / 1e9


def main():
    rng = np.random.default_rng(11)
    codec = registry.factory(
        "jerasure", {"technique": "liberation", "k": "4", "m": "2", "w": "7"}
    )
    k, w = 4, 7
    chunk = 7 * 32768
    rows = xor_schedule.schedule_rows(codec.coding_bitmatrix)
    for batch in (32,):
        shards = tuple(
            jnp.asarray(rng.integers(0, 256, (batch, chunk), np.uint8))
            for _ in range(k)
        )
        nbytes = batch * k * chunk
        for sb in (8, 16, 32):
            ap = make_multiop(rows, k, w, chunk, sb)
            g = loop_gbps(ap, shards, nbytes)
            print(f"multiop sb={sb} batch={batch}: {g:.1f} GB/s", flush=True)

    # correctness vs engine
    small = tuple(
        np.asarray(rng.integers(0, 256, (4, chunk), np.uint8))
        for _ in range(k)
    )
    ap = make_multiop(rows, k, w, chunk, 4)
    outs = ap(*(jnp.asarray(s) for s in small))
    ref = codec.encode_chunks({i: small[i] for i in range(k)})
    ok = all(
        np.array_equal(np.asarray(outs[j]), np.asarray(ref[k + j]))
        for j in range(2)
    )
    print("matches engine:", ok, flush=True)


if __name__ == "__main__":
    main()
