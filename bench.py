"""Flagship benchmark: EC(8,4) Reed-Solomon batched stripe encode,
plus the full BASELINE.json scorecard.

Prints ONE JSON line. Headline fields {"metric", "value", "unit",
"vs_baseline"} report the encode throughput against the 25 GB/s/chip
target (BASELINE.json north star); extra fields cover the rest of the
BASELINE.md scorecard (see the keys in main()).

Methodology (round 5 — the measurement itself is a deliverable;
VERDICT r4 item 5):

1. **Feedback loops.** Each iteration's kernel OUTPUT patches the next
   iteration's INPUT (a 128-byte slice), so iterations are serially
   dependent *through the kernel*. Round-4's loop only perturbed the
   input from the induction variable — with nothing consuming the
   output inside the carry, the runtime overlapped/elided iterations:
   a pure-copy kernel measured flat wall time from 100 to 8100
   iterations. With feedback the same probe scales linearly and
   reproduces the known bf16 matmul rate (~0.7 ms per 4096^3 step).
2. **Working sets larger than VMEM.** v5e has 16 MiB of VMEM; any
   input under that can be served without touching HBM after the
   first pass, inflating "bandwidth" far beyond the roofline. All
   throughput configs here stream >= 64 MB.
3. **Diff-of-minima timing.** t(n1) and t(n2) are each timed `reps`
   times; tunnel hiccups only ADD time, so min(t) is the clean
   estimate of each; per-iter = (min t2 - min t1)/(n2 - n1). The
   paired diffs additionally give a dispersion estimate reported as
   `<key>_iqr` (inter-quartile range of per-iter GB/s across rep
   pairs) for the headline metrics.
4. **Self-calibrated roofline, BOTH axes.** The HBM roofline is
   measured each run with a pure-copy Pallas kernel over a 128 MB
   working set (`hbm_copy_gbps`, read+write): the public 819 GB/s
   v5e figure measures low; r5 observed ~1.1-1.2 TB/s.
   `hbm_roofline_frac` is achieved encode traffic over the
   *measured* roofline — but the flagship bit-plane kernel is
   COMPUTE-bound (512 MACs per data byte at (8,4)), so
   `mxu_util_frac` (achieved int8 TOPS / the 394.7 public peak) is
   its governing roofline; ~0.7 MXU at ~0.33 HBM is the op running
   near ITS ceiling. Note the honest feedback-loop timing reads
   lower than rounds 1-4 across the board (e.g. r3 xxhash32 "99.7"
   -> ~69 now): the old loop let the runtime overlap or elide
   iterations, which note 1's serial dependency forbids.
5. **Tunnel-health gate.** RTT is probed at start and end
   (`tunnel_rtt_ms`, `tunnel_rtt_end_ms`); the host-clock smallop p99
   is annotated `latency_degraded=true` when RTT > 5 ms — under a
   degraded tunnel that number measures the tunnel, not the path.
   Throughput metrics cancel RTT by construction. Round 8: the
   device-clock rows (`smallop_p99_device_ms`, `cluster_p99_ms`)
   replace the host floor with trip-count-differenced device op time
   (loadgen.recorder.DeviceClock) and need no flag.

The reference tool's spirit is kept (big buffer, fixed iteration
count, throughput = bytes/elapsed —
src/test/erasure-code/ceph_erasure_code_benchmark.cc:185-192) with the
timing adapted to remote-device reality.
"""

from __future__ import annotations

import json
import time

import numpy as np

K, M = 8, 4
CHUNK = 1 << 20          # 1 MiB per shard
BATCH = 8                # stripes per dispatch -> 64 MiB input per iter
TARGET_GBPS = 25.0
LAT_CHUNK = 1 << 16      # 64 KiB single-chunk reconstruct latency probe
RTT_HEALTHY_MS = 5.0


def _timed(fn, *args) -> float:
    t0 = time.perf_counter()
    np.asarray(fn(*args))  # readback forces real remote execution
    return time.perf_counter() - t0


#: target kernel-time span between the two iteration counts: the
#: differenced quantity must dwarf tunnel jitter (RTT swings of tens
#: of ms under degradation), so spans auto-scale to ~this much
#: on-device time regardless of per-iteration cost
SPAN_TARGET_S = 0.45
SPAN_MAX_ITERS = 40000


def _loop_stats(loop, data, n1=None, n2=None, reps=4):
    """(per_iter_seconds, iqr_seconds) via diff-of-minima + paired
    diffs. ``loop(data, iters)`` must be feedback-structured.

    Iteration counts auto-scale: a fixed n2=110 makes the differenced
    span ~20 ms for fast kernels — below the degraded tunnel's jitter
    floor, which round-4 bench entries (and an early r5 run that
    printed a 960 GB/s "decode") show produces pure noise. A rough
    warm-run estimate picks n2 so the span is ~SPAN_TARGET_S of real
    kernel time; explicit n1/n2 skip the estimate."""
    if n2 is None:
        # iterative doubling with a MEASURED stop condition: a span
        # estimate derived from two RTT-contaminated samples can be
        # off by orders of magnitude (an early r5 run picked 40000
        # iterations for a 200 us kernel and burned 80 s per metric);
        # doubling stops when the wall-time delta itself clears the
        # target, so the pick is right regardless of jitter. The
        # probe ladder doubles as the warm-up (iters is a traced
        # argument — one compile serves every count).
        base = min(_timed(loop, data, 1) for _ in range(2))
        n2 = 60
        while n2 < SPAN_MAX_ITERS:
            if _timed(loop, data, n2) - base >= SPAN_TARGET_S:
                break
            n2 *= 2
        n2 = min(n2, SPAN_MAX_ITERS)
        n1 = max(1, n2 // 10)
    else:
        for t in (n1, n2):
            _timed(loop, data, t)  # warm/compile
    t1s = [_timed(loop, data, n1) for _ in range(reps)]
    t2s = [_timed(loop, data, n2) for _ in range(reps)]
    per = (min(t2s) - min(t1s)) / (n2 - n1)
    if per <= 0:
        raise RuntimeError("non-positive differenced timing")
    pairs = [
        (b - a) / (n2 - n1) for a, b in zip(sorted(t1s), sorted(t2s))
    ]
    pairs = [p for p in pairs if p > 0]
    if len(pairs) >= 3:
        iqr = float(
            np.percentile(pairs, 75) - np.percentile(pairs, 25)
        )
    else:
        iqr = 0.0
    return per, iqr


def _feedback_loop(apply, opaque: bool):
    """Build the standard feedback loop over [B, C, N] uint8 data:
    out -> 128-byte fold -> patches next input. Opaque (Pallas)
    applies fold a slice (XLA cannot slice through the custom call);
    plain-XLA applies fold the full output via sum, or XLA dead-codes
    the unread majority of the work."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def loop(d0, iters):
        def body(i, carry):
            d, acc = carry
            out = apply(d)
            if opaque:
                fold = jax.lax.dynamic_slice(
                    out, (0,) * (out.ndim - 1) + (0,),
                    (1,) * (out.ndim - 1) + (128,),
                )
                patch = fold.reshape(1, 1, 128) ^ jnp.uint8(i + 1)
                scalar = fold.reshape(-1)[0]
            else:
                scalar = jnp.sum(out, dtype=jnp.uint8)
                patch = jnp.full((1, 1, 128), scalar, jnp.uint8) ^ jnp.uint8(
                    i + 1
                )
            d = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
            return d, acc ^ scalar

        _, acc = jax.lax.fori_loop(0, iters, body, (d0, jnp.uint8(0)))
        return acc

    return loop


def _device_loop_gbps(apply, data, reps=4, opaque=None):
    """(GB/s data-in, iqr GB/s) for `apply` over [B, C, N] uint8."""
    from ceph_tpu.ops import pallas_encode as pe

    batch, k, n = data.shape
    if opaque is None:
        opaque = pe.on_tpu()
    loop = _feedback_loop(apply, opaque)
    per, iqr = _loop_stats(loop, data, reps=reps)
    gbps = batch * k * n / per / 1e9
    return gbps, gbps - batch * k * n / (per + iqr) / 1e9


def _kernel_apply(bmat_np):
    """Device-path bitmatrix apply: pallas kernel on TPU, einsum off."""
    import jax.numpy as jnp

    from ceph_tpu.ops import pallas_encode as pe
    from ceph_tpu.ops.bitplane import gf_encode_bitplane

    if pe.on_tpu():
        return lambda d: pe.gf_encode_bitplane_pallas(bmat_np, d)
    dev = jnp.asarray(bmat_np)
    return lambda d: gf_encode_bitplane(dev, d)




def _device_rand(shape, seed: int):
    """Benchmark data generated ON DEVICE (jax PRNG + cast): a
    degraded tunnel moves host arrays at only a few MB/s, so
    uploading the 64-340 MB working sets dominated the whole run;
    the kernels' cost is data-independent, so device PRNG bytes are
    equivalent and free to produce."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(seed)
    return jax.random.randint(
        key, shape, 0, 256, dtype=jnp.int32
    ).astype(jnp.uint8)


def _measure_roofline(result: dict) -> float:
    """Pure-copy (xor-1) Pallas kernel over 128 MB: the achievable
    HBM read+write rate this run, the denominator for roofline
    fractions. 2D [rows, lanes] layout — the sublane dimension stays
    dense, so no tile padding confounds the number. Falls back to the
    819 GB/s public spec off-TPU."""
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        from ceph_tpu.ops import pallas_encode as pe

        if not pe.on_tpu():
            return 819.0
        # 117 MB in 3.7 MB blocks over few grid steps: big blocks keep
        # per-step overhead out of the denominator (1 MB blocks over
        # 128 steps measured 642 GB/s where this config reads ~1.1 TB/s)
        rows, lanes, sb = 512, 229376, 16

        def kernel(d_ref, o_ref):
            o_ref[:] = d_ref[:] ^ jnp.uint8(1)

        def copy(x):
            return pl.pallas_call(
                kernel,
                grid=(rows // sb,),
                in_specs=[pl.BlockSpec((sb, lanes), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((sb, lanes), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, jnp.uint8),
            )(x)

        @jax.jit
        def loop(d0, iters):
            def body(i, carry):
                d, acc = carry
                out = copy(d)
                fold = jax.lax.dynamic_slice(out, (0, 0), (1, 128))
                d = jax.lax.dynamic_update_slice(
                    d, fold ^ jnp.uint8(i + 1), (0, 0)
                )
                return d, acc ^ fold[0, 0]

            _, acc = jax.lax.fori_loop(
                0, iters, body, (d0, jnp.uint8(0))
            )
            return acc

        data = _device_rand((rows, lanes), 0)
        per, _ = _loop_stats(loop, data)
        gbps = 2 * rows * lanes / per / 1e9  # read + write
        result["hbm_copy_gbps"] = round(gbps, 1)
        return gbps
    except Exception:
        return 819.0


def _measure_device_path(result: dict, roofline: float) -> float:
    import jax.numpy as jnp

    from ceph_tpu.gf import (
        decode_matrix,
        gf_matrix_to_bitmatrix,
        vandermonde_rs_matrix,
    )

    g = vandermonde_rs_matrix(K, M)
    enc_bmat_np = gf_matrix_to_bitmatrix(g[K:, :])

    # Decode config: lose data shards 4-7, survive on 0-3 + all parity
    # (a full-m erasure needing true matrix reconstruct).
    present = [0, 1, 2, 3, 8, 9, 10, 11]
    want = [4, 5, 6, 7]
    dmat = decode_matrix(g, K, present)
    dec_rows = np.stack([dmat[w, :] for w in want])
    dec_bmat_np = gf_matrix_to_bitmatrix(dec_rows)

    data = _device_rand((BATCH, K, CHUNK), 0)

    enc_gbps, enc_iqr = _device_loop_gbps(_kernel_apply(enc_bmat_np), data)
    dec_gbps, dec_iqr = _device_loop_gbps(_kernel_apply(dec_bmat_np), data)

    # single-row reconstruct: the honest "naive repair" comparator
    # for the CLAY metric — rebuilding ONE lost chunk needs a 1-row
    # decode, which is far cheaper per input byte than the full-m
    # reconstruct above (MACs scale with output rows)
    dec1_bmat_np = gf_matrix_to_bitmatrix(dmat[4:5, :])
    dec1_gbps, _ = _device_loop_gbps(
        _kernel_apply(dec1_bmat_np), data, reps=3
    )

    enc_s = BATCH * K * CHUNK / enc_gbps / 1e9
    hbm_gbps = (BATCH * (K + M) * CHUNK) / enc_s / 1e9

    result["value_iqr"] = round(enc_iqr, 2)
    result["decode_gbps"] = round(dec_gbps, 2)
    result["decode_iqr"] = round(dec_iqr, 2)
    result["decode1_gbps"] = round(dec1_gbps, 2)
    result["hbm_gbps"] = round(hbm_gbps, 1)
    result["hbm_roofline_frac"] = round(hbm_gbps / roofline, 3)
    # The flagship kernel is COMPUTE-bound, not HBM-bound: the
    # bit-plane formulation streams [8R, 8F] int8 matmuls (F = K +
    # pad-to-4). MAC accounting comes from the kernel's own packing
    # rule (ops.pallas_encode.mac_stats): 256 MACs per data byte at
    # (8,4) — HALF the round-5 count, whose s=2 block-diagonal stripe
    # pair clocked 512 with every other MAC a structural zero.
    # mxu_util_frac is the achieved rate against the v5e public int8
    # peak (394.7 TOPS); mxu_useful_util_frac discounts the pad
    # columns — the only structural zeros the zero-waste layout has
    # left (identical to mxu_util_frac for the flagship, where
    # K % 4 == 0 means no pad at all).
    from ceph_tpu.ops.pallas_encode import mac_stats

    stats = mac_stats(K, M)
    mxu_tops = 2 * stats["macs_per_byte"] * enc_gbps / 1e3  # TOPS
    result["mxu_tops"] = round(mxu_tops, 1)
    result["mxu_util_frac"] = round(mxu_tops / 394.7, 3)
    result["mxu_useful_util_frac"] = round(
        mxu_tops * stats["useful_frac"] / 394.7, 3
    )
    return enc_gbps


def _measure_baseline_configs(result: dict) -> None:
    """BASELINE configs 1-3 + the ISA envelope max: per-plugin encode
    throughput with the config's exact geometry. Stripe counts sized
    so every working set streams >= 64 MB (methodology note 2)."""
    import jax.numpy as jnp

    from ceph_tpu.gf import (
        cauchy_good_matrix,
        gf_matrix_to_bitmatrix,
        isa_rs_matrix,
        vandermonde_rs_matrix,
    )

    configs = [
        # (result key, generator matrix, k, m, chunk bytes, stripes)
        ("jerasure_k4m2_4k_gbps", vandermonde_rs_matrix(4, 2), 4, 2,
         4096, 4096),
        ("isa_k8m3_64k_gbps", isa_rs_matrix(8, 3), 8, 3, 8192, 1024),
        # 100 KiB chunks as in BASELINE config 3, but 256 stripes
        # (262 MB/iter): honest per-iteration timing makes the old
        # 1 GiB set cost ~7 ms/iter of pure wall time for no extra
        # signal — still 16x VMEM
        ("cauchy_k10m4_1m_gbps", cauchy_good_matrix(10, 4), 10, 4,
         102400, 256),
        # the ISA-L documented envelope max (isa/README:23-24)
        ("isa_k21m4_gbps", isa_rs_matrix(21, 4), 21, 4, 65536, 256),
    ]
    for key, gmat, k, m, chunk, stripes in configs:
        try:
            bmat = gf_matrix_to_bitmatrix(np.asarray(gmat)[k:, :])
            data = _device_rand((stripes, k, chunk), 7)
            gbps, iqr = _device_loop_gbps(
                _kernel_apply(bmat), data, reps=3
            )
            result[key] = round(gbps, 2)
            result[key + "_iqr"] = round(iqr, 2)
        except Exception:
            pass  # scorecard entries are best-effort; headline must print


def _measure_code_families(result: dict) -> None:
    """Family-level device throughput for the packet bit-matrix codes
    and LRC/SHEC, through the REAL codec dispatch path — registry
    factory, route selection, schedule/MXU kernels — not a bare
    matmul. The packet families use the shards form: per-shard arrays
    in, per-shard parity out (stacking the output back into one
    tensor is a relayout copy the real pipeline never performs, so
    the fold XORs 128-byte slices of each parity shard instead).

    Budget trim (round 9, the checksums-trim discipline): ONE warmed
    device buffer is sliced+reshaped into every family's shard set,
    stripe counts are equalized so each working set streams 64-76 MB
    (r5 ran up to 300 MB/iter for no extra signal), and the
    iteration-count ladder runs once on the first family with its
    counts reused everywhere (near-identical bytes/iter).  The old
    per-family ladder + fresh buffers cost the phase 269.5 s in r5 —
    past the tunnel budget once the repair phase gained its aloof
    geometry."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.codecs import registry

    families = [
        # (result key, plugin, profile, chunk bytes, stripes) —
        # stripes sized so k*stripes*chunk streams >= 64 MB (note 2)
        # while every family lands within ~12% of the same bytes/iter
        ("liberation_k4m2_gbps", "jerasure",
         {"technique": "liberation", "k": "4", "m": "2", "w": "7"},
         7 * 16384, 160),
        ("blaum_roth_k4m2_gbps", "jerasure",
         {"technique": "blaum_roth", "k": "4", "m": "2", "w": "6"},
         6 * 16384, 192),
        ("liber8tion_k4m2_gbps", "jerasure",
         {"technique": "liber8tion", "k": "4", "m": "2", "w": "8"},
         8 * 16384, 128),
        ("lrc_k4m2l3_gbps", "lrc",
         {"k": "4", "m": "2", "l": "3"}, 65536, 256),
        ("shec_k4m3c2_gbps", "shec",
         {"k": "4", "m": "3", "c": "2"}, 65536, 256),
    ]
    total = max(
        int(p["k"]) * stripes * chunk
        for _key, _pl, p, chunk, stripes in families
    )
    flat = _device_rand((total,), 11)
    counts = {"n1": None, "n2": None}
    for key, plugin, profile, chunk, stripes in families:
        try:
            codec = registry.factory(plugin, dict(profile))
            k = codec.k

            def apply_dict(shards, codec=codec, k=k):
                parity = codec.encode_chunks(
                    {i: shards[i] for i in range(k)}
                )
                return [parity[j] for j in sorted(parity)]

            sz = stripes * chunk
            shards0 = tuple(
                flat[i * sz : (i + 1) * sz].reshape(stripes, chunk)
                for i in range(k)
            )

            @jax.jit
            def loop(arrs, iters, apply_dict=apply_dict):
                def body(i, carry):
                    arrs, acc = carry
                    outs = apply_dict(arrs)
                    fold = jax.lax.dynamic_slice(
                        outs[0], (0, 0), (1, 128)
                    )
                    scalar = fold[0, 0]
                    for o in outs[1:]:
                        scalar = scalar ^ o[0, 0]
                    first = jax.lax.dynamic_update_slice(
                        arrs[0], fold ^ jnp.uint8(i + 1), (0, 0)
                    )
                    return (first,) + arrs[1:], acc ^ scalar

                _, acc = jax.lax.fori_loop(
                    0, iters, body, (arrs, jnp.uint8(0))
                )
                return acc

            nbytes = stripes * k * chunk
            if counts["n2"] is None:
                per, iqr = _loop_stats(loop, shards0, reps=3)
                counts["n2"] = max(
                    60, int(SPAN_TARGET_S / max(per, 1e-6))
                )
                counts["n1"] = max(1, counts["n2"] // 10)
            else:
                per, iqr = _loop_stats(
                    loop, shards0, n1=counts["n1"], n2=counts["n2"],
                    reps=3,
                )
            result[key] = round(nbytes / per / 1e9, 2)
            result[key + "_iqr"] = round(
                nbytes / per / 1e9 - nbytes / (per + iqr) / 1e9, 2
            )
        except Exception:
            pass  # scorecard entries are best-effort; headline must print


def _measure_sched_superopt(result: dict) -> None:
    """Round-11 phase: the XOR-schedule superoptimizer scorecard.

    Host rows (device-free): per packet family at the bench geometry,
    the raw ones count, selection-form XOR count, post-CSE op count
    and saving fraction (``xor_schedule.cse_stats``) — the numbers the
    tier-1 golden pins assert, recorded next to the measured rates.

    Device rows:
    - ``sched_unopt_liberation_gbps``: the liberation encode
      re-measured with ``ec_sched_opt=false`` — the within-run A/B leg
      against ``liberation_k4m2_gbps`` (code-families phase, optimizer
      on). Same geometry, same session: the pair isolates the CSE'd
      multi-level schedule's effect on the dispatch ceiling.
    - ``lrc_local_repair_gbps``: single-lost-chunk repair on the
      xor-local-parity LRC profile (k=4 m=2 l=3, 64 KiB chunks),
      survivor-bytes-in basis — the locality story's on-device rate:
      3 survivor chunks read instead of k, through the schedule
      engine's w=1 route (BASELINE `lrc_*_gbps >= 200` row).
    """
    try:
        import jax
        import jax.numpy as jnp

        from ceph_tpu.codecs.registry import registry
        from ceph_tpu.ops import xor_schedule
        from ceph_tpu.utils import config
    except Exception:
        return
    fam_profiles = [
        ("liberation", {"technique": "liberation", "k": "4", "m": "2",
                        "w": "7"}),
        ("blaum_roth", {"technique": "blaum_roth", "k": "4", "m": "2",
                        "w": "6"}),
        ("liber8tion", {"technique": "liber8tion", "k": "4", "m": "2",
                        "w": "8"}),
    ]
    for fam, profile in fam_profiles:
        try:
            codec = registry.factory("jerasure", dict(profile))
            st = xor_schedule.cse_stats(codec.coding_bitmatrix)
            result[f"{fam}_sched_raw_xors"] = st["raw_xors"]
            result[f"{fam}_sched_opt_xors"] = st["opt_xors"]
            result[f"{fam}_sched_cse_saving"] = st["saving_frac"]
        except Exception:
            pass

    def encode_loop_gbps(codec, k, chunk, stripes, seed):
        sz = stripes * chunk
        flat = _device_rand((k * sz,), seed)
        shards = tuple(
            flat[i * sz : (i + 1) * sz].reshape(stripes, chunk)
            for i in range(k)
        )

        @jax.jit
        def loop(arrs, iters):
            def body(i, carry):
                arrs, acc = carry
                parity = codec.encode_chunks(
                    {j: arrs[j] for j in range(k)}
                )
                outs = [parity[j] for j in sorted(parity)]
                fold = jax.lax.dynamic_slice(outs[0], (0, 0), (1, 128))
                scalar = fold[0, 0]
                for o in outs[1:]:
                    scalar = scalar ^ o[0, 0]
                first = jax.lax.dynamic_update_slice(
                    arrs[0], fold ^ jnp.uint8(i + 1), (0, 0)
                )
                return (first,) + arrs[1:], acc ^ scalar

            _, acc = jax.lax.fori_loop(
                0, iters, body, (arrs, jnp.uint8(0))
            )
            return acc

        per, iqr = _loop_stats(loop, shards, reps=3)
        g = stripes * k * chunk / per / 1e9
        return g, g - stripes * k * chunk / (per + iqr) / 1e9

    # A/B leg: liberation encode on the PINNED selection-form
    # schedule (the escape hatch) — trace under the override so the
    # route decision compiles with the optimizer off
    try:
        with config.override(ec_sched_opt=False):
            codec = registry.factory(
                "jerasure", dict(fam_profiles[0][1])
            )
            g, iqr = encode_loop_gbps(codec, 4, 7 * 16384, 160, 21)
        result["sched_unopt_liberation_gbps"] = round(g, 2)
        result["sched_unopt_liberation_iqr"] = round(iqr, 2)
    except Exception:
        pass

    # LRC local repair: one lost data chunk, minimum survivors only
    # (3 chunks of the local group), xor local parity -> schedule
    # route on TPU
    try:
        codec = registry.factory(
            "lrc",
            {"k": "4", "m": "2", "l": "3", "local_parity": "xor"},
        )
        chunk, stripes, lost = 65536, 256, 0
        plan = codec.minimum_to_decode(
            {lost}, set(range(codec.k + codec.m)) - {lost}
        )
        keys = sorted(plan)
        sz = stripes * chunk
        flat = _device_rand((len(keys) * sz,), 23)
        arrs0 = tuple(
            flat[i * sz : (i + 1) * sz].reshape(stripes, chunk)
            for i in range(len(keys))
        )

        @jax.jit
        def rloop(arrs, iters):
            def body(i, carry):
                arrs, acc = carry
                out = codec.decode_chunks(
                    {lost}, dict(zip(keys, arrs))
                )[lost]
                fold = jax.lax.dynamic_slice(out, (0, 0), (1, 128))
                first = jax.lax.dynamic_update_slice(
                    arrs[0], fold ^ jnp.uint8(i + 1), (0, 0)
                )
                return (first,) + arrs[1:], acc ^ fold[0, 0]

            _, acc = jax.lax.fori_loop(
                0, iters, body, (arrs, jnp.uint8(0))
            )
            return acc

        nbytes = len(keys) * sz  # survivor bytes read per repair
        per, iqr = _loop_stats(rloop, arrs0, reps=3)
        g = nbytes / per / 1e9
        result["lrc_local_repair_gbps"] = round(g, 2)
        result["lrc_local_repair_iqr"] = round(
            g - nbytes / (per + iqr) / 1e9, 2
        )
        result["lrc_local_repair_survivors"] = len(keys)
    except Exception:
        pass


def _measure_clay_repair(result: dict) -> None:
    """BASELINE config 4 + the general-d envelope: CLAY single-chunk
    repair, helper bytes read per second, device loop with feedback —
    per geometry.  ``clay_repair_*`` is the aloof-free flagship
    (8,4,d=11); ``clay_repair_aloof_*`` the (8,4,d=10) profile whose
    one aloof node exercises the B1/B2 kernel split and per-score-
    group decodes (round 9 — previously that geometry fell back to
    the itemized XLA path at ~20 GB/s).  Each geometry reports
    ``*_time_vs_naive`` against the 1-row reconstruct comparator
    (decode1_gbps); target < 1.0 — MSR repair winning on-chip TIME,
    not just the 0.344x byte ratio."""
    try:
        import jax
        import jax.numpy as jnp

        from ceph_tpu.codecs.registry import registry
    except Exception:
        return
    geometries = [
        ("clay_repair", {"k": "8", "m": "4", "d": "11"}),
        ("clay_repair_aloof", {"k": "8", "m": "4", "d": "10"}),
    ]
    counts: dict = {"n1": None, "n2": None}
    for key, profile in geometries:
        try:
            codec = registry.factory("clay", profile)
            k, m, d = codec.k, codec.m, codec.d
            n = k + m
            sub = codec.get_sub_chunk_count()
            chunk = codec.get_chunk_size(k << 16)  # 64 KiB chunks
            sc = chunk // sub
            stripes = 256
            lost = k + 1  # a parity chunk: full helper-plane read path

            plan = codec.minimum_to_decode(
                {lost}, set(range(n)) - {lost}
            )
            # helper bytes generated ON DEVICE: repair cost is
            # data-independent, and correctness is covered by the
            # test suite + dryrun — the bench only times the plane
            # program (the old host-side encode of a 128 MB codeword
            # + 45 MB upload cost minutes through a degraded tunnel)
            helper, read = {}, 0
            for hseed, (node, ranges) in enumerate(sorted(plan.items())):
                nbytes = sum(cnt for _idx, cnt in ranges) * sc
                read += stripes * nbytes
                helper[node] = _device_rand(
                    (stripes, nbytes), 100 + hseed
                )
            keys = sorted(helper)

            @jax.jit
            def loop(arrs, iters, codec=codec, keys=keys, lost=lost):
                def body(i, carry):
                    arrs, acc = carry
                    out = codec.repair(
                        {lost}, dict(zip(keys, arrs))
                    )[lost]
                    fold = jax.lax.dynamic_slice(out, (0, 0), (1, 128))
                    first = jax.lax.dynamic_update_slice(
                        arrs[0], fold ^ jnp.uint8(i + 1), (0, 0)
                    )
                    return (first,) + arrs[1:], acc + jnp.sum(
                        fold, dtype=jnp.uint32
                    )

                _, acc = jax.lax.fori_loop(
                    0, iters, body, (arrs, jnp.uint32(0))
                )
                return acc

            arrs = tuple(helper[kk] for kk in keys)
            if counts["n2"] is None:
                per, iqr = _loop_stats(loop, arrs, reps=3)
                # reuse the flagship's auto-scaled span for the other
                # geometries (bytes/iter within ~10%, checksums-trim
                # discipline) — the doubling ladder runs once
                counts["n2"] = max(
                    60, int(SPAN_TARGET_S / max(per, 1e-6))
                )
                counts["n1"] = max(1, counts["n2"] // 10)
            else:
                per, iqr = _loop_stats(
                    loop, arrs, n1=counts["n1"], n2=counts["n2"],
                    reps=3,
                )
            gbps = read / per / 1e9
            result[f"{key}_gbps"] = round(gbps, 2)
            result[f"{key}_iqr"] = round(
                gbps - read / (per + iqr) / 1e9, 2
            )
            # The hardware-independent MSR story: helper bytes read
            # as a fraction of the k*chunk a naive decode would read.
            result[f"{key}_read_frac"] = round(
                read / (k * chunk * stripes), 3
            )
            dec1 = result.get("decode1_gbps")
            if dec1:
                naive_s = k * chunk * stripes / (dec1 * 1e9)
                result[f"{key}_time_vs_naive"] = round(
                    per / naive_s, 2
                )
        except Exception:
            pass


def _measure_smallop_dispatch(result: dict) -> None:
    """Small-op (64 KiB = 8 x 8 KiB) encode throughput: the per-op
    device path vs the native-ring streaming dispatcher aggregating 16
    concurrent writers (pipeline/dispatcher.py). Latency-class metric:
    annotated when the tunnel is degraded."""
    try:
        import threading

        import jax.numpy as jnp

        from ceph_tpu import native
        from ceph_tpu.codecs.registry import registry
        from ceph_tpu.pipeline.dispatcher import StreamingDispatcher

        if not native.available():
            return
        codec = registry.factory("isa", {"k": str(K), "m": str(M)})
        k, chunk = K, 8192
        rng = np.random.default_rng(5)

        ops = [
            jnp.asarray(rng.integers(0, 256, (k, chunk), np.uint8))
            for _ in range(16)
        ]
        for o in ops[:2]:  # warm/compile
            p = codec.encode_chunks({i: o[i] for i in range(k)})
            np.asarray(p[k])
        t0 = time.perf_counter()
        for o in ops:
            p = codec.encode_chunks({i: o[i] for i in range(k)})
            np.asarray(p[k])
        perop_s = (time.perf_counter() - t0) / len(ops)
        perop_gbps = k * chunk / perop_s / 1e9

        disp = StreamingDispatcher(codec, window_s=0.002)
        try:
            datas = rng.integers(
                0, 256, (16, k, chunk), np.uint8
            )
            lat: list[float] = []
            lat_lock = threading.Lock()

            def worker(i):
                for _ in range(24):
                    t1 = time.perf_counter()
                    disp.encode_sync(datas[i])
                    dt = time.perf_counter() - t1
                    with lat_lock:
                        lat.append(dt)

            disp.encode_sync(datas[0])  # warm the batched shape
            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(16)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        finally:
            disp.stop()
        total_bytes = 16 * 24 * k * chunk
        stream_gbps = total_bytes / wall / 1e9
        result["smallop_perop_gbps"] = round(perop_gbps, 4)
        result["smallop_stream_gbps"] = round(stream_gbps, 4)
        result["smallop_speedup"] = round(stream_gbps / perop_gbps, 1)
        lat_ms = np.array(lat) * 1e3
        result["smallop_p99_ms"] = round(
            float(np.percentile(lat_ms, 99)), 2
        )
        # device-clock row (VERDICT weak #6): host p99 with the
        # constant floor (tunnel RTT + dispatch overhead, pinned by
        # the fastest op) replaced by the trip-count-differenced
        # device op time — tunnel-RTT independent, so this row needs
        # no latency_degraded flag (see loadgen.recorder.DeviceClock)
        try:
            from ceph_tpu.loadgen.recorder import DeviceClock

            dev_s = DeviceClock.measure(codec, chunk)
            if dev_s is not None:
                result["smallop_p99_device_ms"] = round(
                    float(np.percentile(lat_ms, 99))
                    - float(lat_ms.min()) + dev_s * 1e3, 3
                )
        except Exception:
            pass
    except Exception:
        pass


def _measure_single_core(result: dict, enc_gbps: float) -> None:
    """Native C single-core GF encode — the ISA-L-role CPU baseline
    (BASELINE.md target: >= 10x). Same k/m, 1 MiB chunks."""
    try:
        from ceph_tpu import native
        from ceph_tpu.gf import vandermonde_rs_matrix

        if not native.available():
            return
        g = vandermonde_rs_matrix(K, M)
        coding = np.ascontiguousarray(g[K:, :])
        rng = np.random.default_rng(1)
        data = rng.integers(0, 256, (K, CHUNK), np.uint8)
        native.gf_matrix_encode(coding, data)  # warm
        iters, t0 = 8, time.perf_counter()
        for _ in range(iters):
            native.gf_matrix_encode(coding, data)
        dt = (time.perf_counter() - t0) / iters
        cpu_gbps = K * CHUNK / dt / 1e9
        result["single_core_gbps"] = round(cpu_gbps, 3)
        result["vs_single_core"] = round(enc_gbps / cpu_gbps, 1)
    except Exception:
        pass  # baseline is best-effort; the headline must still print


def _measure_reconstruct_latency(result: dict) -> None:
    """p50/p99 single-chunk reconstruct on the host small-op path —
    true per-op wall time: numpy in, numpy out, no device round
    trip (so NOT tunnel-sensitive)."""
    from ceph_tpu.codecs.registry import registry

    codec = registry.factory("isa", {"k": str(K), "m": str(M)})
    rng = np.random.default_rng(2)
    data = {i: rng.integers(0, 256, (LAT_CHUNK,), np.uint8) for i in range(K)}
    parity = codec.encode_chunks(data)
    chunks = {**data, **parity}
    del chunks[5]  # one lost data shard, the common repair case
    lat = []
    for _ in range(200):
        t0 = time.perf_counter()
        out = codec.decode_chunks({5}, chunks)
        np.asarray(out[5])
        lat.append(time.perf_counter() - t0)
    lat_ms = np.array(lat) * 1e3
    result["reconstruct_p50_ms"] = round(float(np.percentile(lat_ms, 50)), 3)
    result["reconstruct_p99_ms"] = round(float(np.percentile(lat_ms, 99)), 3)


def _measure_checksums(result: dict) -> None:
    """BASELINE config 5 (CRC32C over 4/16/64 KiB) + xxhash32/64.
    Feedback form: the per-block hash vector's first lanes patch the
    next input; the accumulator folds the full hash vector (the hash
    path is partly plain XLA — a sliced consumer would let XLA
    dead-code most blocks).

    Budget trim (round 7): ONE warmed 32 MB device buffer is reshaped
    for every block size (the kernels are data-independent, and 32 MB
    still streams 2x VMEM), the iteration-count ladder runs once on
    the first config and its counts are reused everywhere (identical
    bytes/iter => near-identical per-iter time), and reps drop to 3.
    The old per-key ladder + fresh 64 MB buffers cost the section
    ~225 s — past the tunnel budget once the fused-path phase landed."""
    try:
        import jax
        import jax.numpy as jnp

        from ceph_tpu.checksum.crc32c import crc32c_device
    except Exception:
        return

    size = 32 << 20
    flat = _device_rand((size,), 3)
    counts = {"n1": None, "n2": None}

    def hash_loop_gbps(hash_fn, blocks, reps=3):
        nblocks, block = blocks.shape

        @jax.jit
        def loop(b0, iters):
            def body(i, carry):
                b, acc = carry
                h = hash_fn(b)  # [nblocks] uint32
                s = jnp.sum(h, dtype=jnp.uint32)
                patch = (
                    jax.lax.dynamic_slice(h, (0,), (32,))
                    .astype(jnp.uint8)
                    .reshape(1, 32)
                    ^ jnp.uint8(i + 1)
                )
                b = jax.lax.dynamic_update_slice(b, patch, (0, 0))
                return b, acc + s

            _, acc = jax.lax.fori_loop(
                0, iters, body, (b0, jnp.uint32(0))
            )
            return acc

        if counts["n2"] is None:
            per, iqr = _loop_stats(loop, blocks, reps=reps)
            # reuse this config's auto-scaled span for the rest of the
            # section: every config streams the same bytes per iter
            base = min(_timed(loop, blocks, 1) for _ in range(2))
            n2 = max(60, int(SPAN_TARGET_S / max(per, 1e-6)))
            counts["n1"], counts["n2"] = max(1, n2 // 10), n2
        else:
            per, iqr = _loop_stats(
                loop, blocks, n1=counts["n1"], n2=counts["n2"],
                reps=reps,
            )
        g = nblocks * block / per / 1e9
        return g, g - nblocks * block / (per + iqr) / 1e9

    for key, block in (
        ("crc32c_gbps", 4096),
        ("crc32c_16k_gbps", 16384),
        ("crc32c_64k_gbps", 65536),
    ):
        try:
            blocks = flat.reshape(size // block, block)
            g, iqr = hash_loop_gbps(
                lambda b: crc32c_device(b, 0xFFFFFFFF), blocks
            )
            result[key] = round(g, 1)
            result[key + "_iqr"] = round(iqr, 1)
        except Exception:
            pass
    try:
        from ceph_tpu.checksum.xxhash import xxh32_device, xxh64_device

        blocks = flat.reshape(size // 4096, 4096)
        g, iqr = hash_loop_gbps(lambda b: xxh32_device(b), blocks)
        result["xxhash32_gbps"] = round(g, 1)
        result["xxhash32_iqr"] = round(iqr, 1)

        def xx64(b):
            h = xxh64_device(b)
            return (h[0] ^ h[1]).astype(jnp.uint32) if isinstance(
                h, tuple
            ) else h.astype(jnp.uint32)

        g, iqr = hash_loop_gbps(xx64, blocks)
        result["xxhash64_gbps"] = round(g, 1)
        result["xxhash64_iqr"] = round(iqr, 1)
    except Exception:
        pass


def _measure_fused_write_path(result: dict, enc_gbps: float) -> None:
    """Tentpole metric (round 7): the whole write path's device cost —
    parity AND per-4K-block crc32c for all k+m shards — three ways:

    - ``fused_write_path_gbps``: the fused encode+csum kernel, ONE
      pass over the data while it is resident for the encode matmul;
    - ``write_path_sep_gbps``: the plain encode kernel followed by a
      separate ``crc32c_device`` pass over data + parity (re-reads
      every byte encode just wrote — the extra HBM pass fusion kills);
    - ``write_path_host_gbps``: device encode + HOST csum, composed
      analytically from a 4 MB host-hash sample (hashing 96 MB/iter
      on the host directly would burn minutes of tunnel time for a
      number whose magnitude is not in doubt).

    ``fused_vs_sep`` is the headline ratio (acceptance: >= 1.3x)."""
    try:
        import jax
        import jax.numpy as jnp

        from ceph_tpu.checksum.crc32c import crc32c_device
        from ceph_tpu.gf import (
            gf_matrix_to_bitmatrix,
            vandermonde_rs_matrix,
        )
        from ceph_tpu.ops import pallas_encode as pe

        if not pe.on_tpu():
            return
        cb = 4096
        g = vandermonde_rs_matrix(K, M)
        bmat = gf_matrix_to_bitmatrix(g[K:, :])
        data = _device_rand((BATCH, K, CHUNK), 9)
        nbytes = BATCH * K * CHUNK

        def csum_feedback(p, cs, d, i):
            # fold BOTH outputs into the next input: iterations are
            # serially dependent through parity AND csums, so neither
            # leg can be elided/overlapped (methodology note 1)
            fold = jax.lax.dynamic_slice(p, (0, 0, 0), (1, 1, 128))
            cfold = jnp.tile(
                jax.lax.dynamic_slice(
                    cs, (0, 0, 0), (1, 1, 32)
                ).astype(jnp.uint8),
                (1, 1, 4),
            )
            patch = fold ^ cfold ^ jnp.uint8(i + 1)
            d = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
            return d, fold.reshape(-1)[0] ^ cfold.reshape(-1)[0]

        @jax.jit
        def loop_fused(d0, iters):
            def body(i, carry):
                d, acc = carry
                p, cs = pe.gf_encode_csum_bitplane_pallas(bmat, d, cb)
                d, scalar = csum_feedback(p, cs, d, i)
                return d, acc ^ scalar

            _, acc = jax.lax.fori_loop(
                0, iters, body, (d0, jnp.uint8(0))
            )
            return acc

        @jax.jit
        def loop_sep(d0, iters):
            def body(i, carry):
                d, acc = carry
                p = pe.gf_encode_bitplane_pallas(bmat, d)
                cs_d = crc32c_device(
                    d.reshape(BATCH, K, CHUNK // cb, cb), 0
                )
                cs_p = crc32c_device(
                    p.reshape(BATCH, M, CHUNK // cb, cb), 0
                )
                cs = jnp.concatenate([cs_d, cs_p], axis=1)
                d, scalar = csum_feedback(p, cs, d, i)
                return d, acc ^ scalar

            _, acc = jax.lax.fori_loop(
                0, iters, body, (d0, jnp.uint8(0))
            )
            return acc

        per_f, iqr_f = _loop_stats(loop_fused, data, reps=3)
        per_s, _ = _loop_stats(loop_sep, data, reps=3)
        fused_gbps = nbytes / per_f / 1e9
        result["fused_write_path_gbps"] = round(fused_gbps, 2)
        result["fused_write_path_iqr"] = round(
            fused_gbps - nbytes / (per_f + iqr_f) / 1e9, 2
        )
        result["write_path_sep_gbps"] = round(nbytes / per_s / 1e9, 2)
        result["fused_vs_sep"] = round(per_s / per_f, 2)

        # host-csum comparator: sample the host scalar rate, compose
        from ceph_tpu.checksum import crc32c_scalar

        sample = np.random.default_rng(10).integers(
            0, 256, 4 << 20, np.uint8
        ).tobytes()
        crc32c_scalar(0xFFFFFFFF, sample[:cb])  # warm native load
        t0 = time.perf_counter()
        for off in range(0, len(sample), cb):
            crc32c_scalar(0xFFFFFFFF, sample[off : off + cb])
        host_gbps = len(sample) / (time.perf_counter() - t0) / 1e9
        result["host_csum_gbps"] = round(host_gbps, 3)
        csum_bytes = BATCH * (K + M) * CHUNK
        t_total = nbytes / (enc_gbps * 1e9) + csum_bytes / (
            host_gbps * 1e9
        )
        result["write_path_host_gbps"] = round(
            nbytes / t_total / 1e9, 2
        )
    except Exception:
        pass  # scorecard entries are best-effort; headline must print


def _measure_cluster(result: dict, enc_gbps: float) -> None:
    """Live-tier phase (round 8): mixed workload + OSD kill/revive
    over the real mini-cluster — cluster_gbps / cluster_iops /
    cluster_p99_ms (device clock), the degraded-window cut, the
    kernel-vs-cluster efficiency ratio, the coalesce/degraded-link
    A/Bs, and the round-14 tracked-vs-untracked observability A/B
    (trace_overhead_frac, acceptance < 0.02). See
    loadgen/bench_phase.py for methodology; sized by
    CEPH_TPU_BENCH_CLUSTER_OPS."""
    try:
        from ceph_tpu.loadgen.bench_phase import measure_cluster

        measure_cluster(result, enc_gbps)
    except Exception:
        pass  # scorecard entries are best-effort; headline must print


def _measure_qos(result: dict) -> None:
    """Multi-tenant QoS phase (round 19): the noisy-neighbor A/B —
    tenant A's p99 solo, under a tenant-B flood + concurrent recovery
    with dmClock QoS armed, and the same storm with osd_op_qos=false
    (the escape hatch) — plus the recovery-slosh curve
    (time_to_recovered_s vs client p99 across high_client / balanced /
    high_recovery). See loadgen/bench_phase.py:measure_qos; sized by
    CEPH_TPU_BENCH_QOS_OPS."""
    try:
        from ceph_tpu.loadgen.bench_phase import measure_qos

        measure_qos(result)
    except Exception:
        pass  # scorecard entries are best-effort; headline must print


def _measure_transport(result: dict, enc_gbps: float) -> None:
    """Messenger-v2 transport phase (round 20): the within-run
    transport x codec A/B grid (tcp/shm_ring x python/native frame
    codec) with a per-leg cluster-vs-kernel fraction, the shm-ring
    lane headline (shm_ring_gbps + chunk/byte traffic proof), the
    native-codec speedup, and the op-shard head-of-line rows — the
    flood x kill latency-spread ladder at 1 vs 4 shards plus the
    deterministic parked-shard sibling probe. See
    loadgen/bench_phase.py:measure_transport; sized by
    CEPH_TPU_BENCH_TRANSPORT_OPS."""
    try:
        from ceph_tpu.loadgen.bench_phase import measure_transport

        measure_transport(result, enc_gbps)
    except Exception:
        pass  # scorecard entries are best-effort; headline must print


def _tunnel_rtt_ms() -> float | None:
    """1-byte-readback device round trip: the tunnel-health probe."""
    try:
        import jax
        import jax.numpy as jnp

        x = jnp.asarray(np.zeros((8, 8192), np.uint8))
        f = jax.jit(lambda a: (a ^ 1)[0, :1])
        np.asarray(f(x))  # warm
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(f(x))
            samples.append(time.perf_counter() - t0)
        return round(min(samples) * 1e3, 2)
    except Exception:
        return None


def _phase(name):
    """Progress + wall time per phase on stderr (stdout carries only
    the one JSON line; the driver tails stderr when a run stalls)."""
    import contextlib
    import sys

    @contextlib.contextmanager
    def cm():
        t0 = time.perf_counter()
        try:
            yield
        finally:
            print(
                f"[bench] {name}: {time.perf_counter() - t0:.1f}s",
                file=sys.stderr, flush=True,
            )

    return cm()


def main() -> None:
    result: dict = {}
    rtt = _tunnel_rtt_ms()
    if rtt is not None:
        result["tunnel_rtt_ms"] = rtt
    with _phase("roofline"):
        roofline = _measure_roofline(result)
    with _phase("device_path"):
        enc_gbps = _measure_device_path(result, roofline)
    with _phase("baseline_configs"):
        _measure_baseline_configs(result)
    with _phase("code_families"):
        _measure_code_families(result)
    with _phase("sched_superopt"):
        _measure_sched_superopt(result)
        # the dispatch-path ceiling: best packet-family rate through
        # the (optimized) schedule engine this run — the > 537 GB/s
        # round-11 target row
        rates = [
            result.get(k)
            for k in (
                "liberation_k4m2_gbps",
                "blaum_roth_k4m2_gbps",
                "liber8tion_k4m2_gbps",
            )
        ]
        rates = [r for r in rates if isinstance(r, (int, float))]
        if rates:
            result["sched_dispatch_ceiling_gbps"] = round(
                max(rates), 2
            )
    with _phase("clay_repair"):
        _measure_clay_repair(result)
    degraded = rtt is None or rtt > RTT_HEALTHY_MS
    with _phase("smallop"):
        _measure_smallop_dispatch(result)
    with _phase("single_core"):
        _measure_single_core(result, enc_gbps)
    with _phase("reconstruct_latency"):
        _measure_reconstruct_latency(result)
    with _phase("checksums"):
        _measure_checksums(result)
    with _phase("fused_write_path"):
        _measure_fused_write_path(result, enc_gbps)
    with _phase("cluster"):
        _measure_cluster(result, enc_gbps)
    with _phase("qos"):
        _measure_qos(result)
    with _phase("transport"):
        _measure_transport(result, enc_gbps)
    rtt_end = _tunnel_rtt_ms()
    if rtt_end is not None:
        result["tunnel_rtt_end_ms"] = rtt_end
        degraded = degraded or rtt_end > RTT_HEALTHY_MS
    if (
        "smallop_p99_ms" in result
        and "smallop_p99_device_ms" not in result
    ):
        # host-clock small-op latency measures the tunnel, not the
        # path, when RTT is degraded — say so in-band. The device-
        # clock rows (smallop_p99_device_ms, cluster_p99_ms) are
        # tunnel-independent by construction and retire this flag.
        result["latency_degraded"] = bool(degraded)
    print(
        json.dumps(
            {
                "metric": f"EC({K},{M}) reed_sol_van batched stripe encode",
                "value": round(enc_gbps, 2),
                "unit": "GB/s data-in per chip",
                "vs_baseline": round(enc_gbps / TARGET_GBPS, 3),
                **result,
            }
        )
    )


if __name__ == "__main__":
    main()
