"""Flagship benchmark: EC(8,4) Reed-Solomon batched stripe encode.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline target: 25 GB/s/chip on TPU v5e-1 (BASELINE.json north star).
``vs_baseline`` is the ratio value / 25.

Methodology — honest under the axon device tunnel, where
``block_until_ready`` resolves without waiting for remote execution
and any real sync costs a ~0.1-0.5 s round trip:

1. The iteration loop runs ON DEVICE (``lax.fori_loop``); each
   iteration perturbs the input (so the encode is not loop-invariant)
   and XOR-folds the parity into an accumulator the final readback
   depends on — execution cannot be elided or overlapped away.
2. Work is forced by reading back one byte of the accumulator
   (``np.asarray``), not by ``block_until_ready``.
3. The fixed tunnel round trip is cancelled by differencing two trip
   counts: per_iter = (t(N2) - t(N1)) / (N2 - N1).
4. A perturb-only loop measured the same way is subtracted so the
   reported number is the encode alone.

The reference tool's spirit is kept (big buffer, fixed iteration
count, throughput = bytes/elapsed —
src/test/erasure-code/ceph_erasure_code_benchmark.cc) with the timing
adapted to remote-device reality.
"""

from __future__ import annotations

import json
import time

import numpy as np

K, M = 8, 4
CHUNK = 1 << 20          # 1 MiB per shard
BATCH = 8                # stripes per dispatch -> 64 MiB input per iter
N1, N2 = 10, 110  # large span: the diff must dwarf tunnel RTT jitter
TARGET_GBPS = 25.0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ceph_tpu.gf import gf_matrix_to_bitmatrix, vandermonde_rs_matrix
    from ceph_tpu.ops.bitplane import gf_encode_bitplane
    from ceph_tpu.ops import pallas_encode as pe

    g = vandermonde_rs_matrix(K, M)
    bmat_np = gf_matrix_to_bitmatrix(g[K:, :])
    bmat = jnp.asarray(bmat_np)
    rng = np.random.default_rng(0)
    data = jnp.asarray(
        rng.integers(0, 256, (BATCH, K, CHUNK)).astype(np.uint8)
    )

    # The codec's TPU path: fused Pallas MXU kernel (einsum off-TPU).
    use_pallas = pe.on_tpu() and pe.supported(data.shape)
    if use_pallas:
        big = jnp.asarray(pe._folded_bitmatrix(bmat_np, pe.FOLD))

        def encode(bm, d):
            return pe._encode_tiled(big, d, pe.FOLD, interpret=False)
    else:

        def encode(bm, d):
            return gf_encode_bitplane(bm, d)

    @jax.jit
    def loop_enc(bmat, data, iters):
        def body(i, carry):
            d, acc = carry
            d = jnp.bitwise_xor(d, jnp.uint8(i + 1))
            p = encode(bmat, d)
            return d, jnp.bitwise_xor(acc, p)

        _, acc = jax.lax.fori_loop(
            0, iters, body,
            (data, jnp.zeros((BATCH, M, CHUNK), jnp.uint8)),
        )
        return acc[0, 0, 0]

    @jax.jit
    def loop_perturb(data, iters):
        def body(i, carry):
            d, acc = carry
            d = jnp.bitwise_xor(d, jnp.uint8(i + 1))
            return d, jnp.bitwise_xor(acc, d[:, :M, :])

        _, acc = jax.lax.fori_loop(
            0, iters, body,
            (data, jnp.zeros((BATCH, M, CHUNK), jnp.uint8)),
        )
        return acc[0, 0, 0]

    def timed(fn, *args) -> float:
        t0 = time.perf_counter()
        np.asarray(fn(*args))  # readback forces real remote execution
        return time.perf_counter() - t0

    # compile + warm both trip counts
    for n in (N1, N2):
        timed(loop_enc, bmat, data, n)
        timed(loop_perturb, data, n)

    # Repeat and keep the minimum: tunnel latency jitter is additive,
    # so the noise floor is the honest estimate.
    def per_iter(fn, *args) -> float:
        best = float("inf")
        for _ in range(3):
            d = (timed(fn, *args, N2) - timed(fn, *args, N1)) / (N2 - N1)
            best = min(best, d)
        return best

    per_iter_full = per_iter(loop_enc, bmat, data)
    per_iter_perturb = per_iter(loop_perturb, data)
    enc_s = max(per_iter_full - per_iter_perturb, 1e-9)

    gbps = BATCH * K * CHUNK / enc_s / 1e9
    print(
        json.dumps(
            {
                "metric": f"EC({K},{M}) reed_sol_van batched stripe encode",
                "value": round(gbps, 2),
                "unit": "GB/s data-in per chip",
                "vs_baseline": round(gbps / TARGET_GBPS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
