"""Flagship benchmark: EC(8,4) Reed-Solomon batched stripe encode,
plus the full BASELINE.json scorecard.

Prints ONE JSON line. Headline fields {"metric", "value", "unit",
"vs_baseline"} report the encode throughput against the 25 GB/s/chip
target (BASELINE.json north star); extra fields cover the rest of the
BASELINE.md scorecard:

  decode_gbps        on-chip reconstruct of 4 lost data shards from 8
                     survivors (same bytes-in basis as encode)
  vs_single_core     encode speedup over the native C single-core GF
                     path (the ISA-L-role baseline, BASELINE.md target
                     ">= 10x"); absent if the native lib is unavailable
  hbm_gbps /         achieved HBM traffic (data-in + parity-out per
  hbm_roofline_frac  encode) vs the ~819 GB/s v5e roofline
  reconstruct_p50_ms / p99  single-chunk (64 KiB) reconstruct latency on
                     the host small-op path (true per-op wall time — the
                     low-latency path beside the bulk device path)
  jerasure_k4m2_4k_gbps   BASELINE config 1: reed_sol_van k=4 m=2,
                     4 KiB chunks, batched stripes
  isa_k8m3_64k_gbps  BASELINE config 2: ISA-L RS k=8 m=3, 64 KiB stripe
  cauchy_k10m4_1m_gbps  BASELINE config 3: cauchy_good k=10 m=4, 1 MiB
                     object, 1024-stripe batch
  clay_repair_gbps   BASELINE config 4: CLAY (8,4,d=11) MSR single-chunk
                     repair, helper-bytes-read basis, host wall time
  crc32c_gbps / crc32c_16k_gbps / crc32c_64k_gbps  BASELINE config 5:
                     deep-scrub CRC32C over 4/16/64 KiB blocks
  xxhash32_gbps / xxhash64_gbps  the remaining Checksummer algorithms

Methodology — honest under the axon device tunnel, where
``block_until_ready`` resolves without waiting for remote execution
and any real sync costs a ~0.1-0.5 s round trip:

1. The iteration loop runs ON DEVICE (``lax.fori_loop``); each
   iteration perturbs the input (so the encode is not loop-invariant)
   and XOR-folds the parity into an accumulator the final readback
   depends on — execution cannot be elided or overlapped away.
2. Work is forced by reading back one byte of the accumulator
   (``np.asarray``), not by ``block_until_ready``.
3. The fixed tunnel round trip is cancelled by differencing two trip
   counts: per_iter = (t(N2) - t(N1)) / (N2 - N1).
4. A perturb-only loop measured the same way is subtracted so the
   reported number is the kernel alone.
5. Differenced estimates are noisy under tunnel-latency jitter — a
   hiccup on the short trip makes a diff NEGATIVE. Each estimate is
   the median of the positive diffs over several repeats (r1 took the
   min, which once picked a glitch and printed 6.7e7 GB/s).

The reference tool's spirit is kept (big buffer, fixed iteration
count, throughput = bytes/elapsed —
src/test/erasure-code/ceph_erasure_code_benchmark.cc) with the timing
adapted to remote-device reality. CLAY repair is host wall time (the
small-op path), like the reference's per-call clock.
"""

from __future__ import annotations

import json
import time

import numpy as np

K, M = 8, 4
CHUNK = 1 << 20          # 1 MiB per shard
BATCH = 8                # stripes per dispatch -> 64 MiB input per iter
N1, N2 = 10, 110  # large span: the diff must dwarf tunnel RTT jitter
REPS = 5
TARGET_GBPS = 25.0
V5E_HBM_GBPS = 819.0     # v5e-1 HBM bandwidth (public spec)
LAT_CHUNK = 1 << 16      # 64 KiB single-chunk reconstruct latency probe


def _timed(fn, *args) -> float:
    t0 = time.perf_counter()
    np.asarray(fn(*args))  # readback forces real remote execution
    return time.perf_counter() - t0


def _per_iter(fn, *args, n1=N1, n2=N2, reps=REPS) -> float:
    """Median of positive differenced estimates (see module docstring)."""
    diffs = []
    for _ in range(reps):
        d = (_timed(fn, *args, n2) - _timed(fn, *args, n1)) / (n2 - n1)
        if d > 0:
            diffs.append(d)
    if not diffs:
        raise RuntimeError("all differenced timings were negative")
    return float(np.median(diffs))


def _device_loop_gbps(apply, data, n1=N1, n2=N2, reps=REPS):
    """GB/s data-in for `apply` over [B, K, N] uint8 `data`.

    On-device loop where the per-iteration bookkeeping is NEGLIGIBLE
    by construction: the input is perturbed only in a 128-byte slice
    (the kernel still cannot be hoisted — its input changed) and only
    a 128-byte slice of the output feeds the accumulator the readback
    depends on (the kernel still runs fully — pallas output is
    opaque to XLA, and the full HBM write happens). No perturb-loop
    subtraction, which was fragile when kernel time ~ perturb time:
    two noisy estimates subtracted once produced a 2 TB/s "decode".

    Off-TPU the apply is plain XLA (einsum), which a sliced consumer
    WOULD dead-code down to 1/N of the work — there the accumulator
    folds an xor-sum over the whole output instead (slower loop, but
    off-TPU numbers are not the recorded ones)."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.ops import pallas_encode as pe

    batch, k, n = data.shape
    opaque = pe.on_tpu()  # pallas path: XLA cannot slice through it

    @jax.jit
    def loop(d0, iters):
        def body(i, carry):
            d, acc = carry
            patch = (
                jax.lax.dynamic_slice(d, (0, 0, 0), (1, 1, 128))
                ^ jnp.uint8(i + 1)
            )
            d = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
            out = apply(d)
            if opaque:
                fold = jax.lax.dynamic_slice(
                    out, (0, 0, 0), (1, 1, 128)
                )[0, 0, 0]
            else:
                fold = jnp.sum(out, dtype=jnp.uint8)
            return d, acc ^ fold

        _, acc = jax.lax.fori_loop(
            0, iters, body, (d0, jnp.uint8(0))
        )
        return acc

    for trips in (n1, n2):
        _timed(loop, data, trips)
    dt = _per_iter(loop, data, n1=n1, n2=n2, reps=reps)
    return batch * k * n / dt / 1e9


def _kernel_apply(bmat_np):
    """Device-path bitmatrix apply: pallas kernel on TPU, einsum off."""
    import jax.numpy as jnp

    from ceph_tpu.ops import pallas_encode as pe
    from ceph_tpu.ops.bitplane import gf_encode_bitplane

    if pe.on_tpu():
        return lambda d: pe.gf_encode_bitplane_pallas(bmat_np, d)
    dev = jnp.asarray(bmat_np)
    return lambda d: gf_encode_bitplane(dev, d)


def _measure_device_path(result: dict) -> float:
    import jax.numpy as jnp

    from ceph_tpu.gf import (
        decode_matrix,
        gf_matrix_to_bitmatrix,
        vandermonde_rs_matrix,
    )

    g = vandermonde_rs_matrix(K, M)
    enc_bmat_np = gf_matrix_to_bitmatrix(g[K:, :])

    # Decode config: lose data shards 4-7, survive on 0-3 + all parity
    # (the exhaustive-erasures tool's worst standard case: a full-m
    # erasure needing true matrix reconstruct, not passthrough).
    present = [0, 1, 2, 3, 8, 9, 10, 11]
    want = [4, 5, 6, 7]
    dmat = decode_matrix(g, K, present)  # [k, len(present)]
    dec_rows = np.stack([dmat[w, :] for w in want])
    dec_bmat_np = gf_matrix_to_bitmatrix(dec_rows)

    rng = np.random.default_rng(0)
    data = jnp.asarray(
        rng.integers(0, 256, (BATCH, K, CHUNK)).astype(np.uint8)
    )

    enc_gbps = _device_loop_gbps(_kernel_apply(enc_bmat_np), data)
    dec_gbps = _device_loop_gbps(_kernel_apply(dec_bmat_np), data)

    enc_s = BATCH * K * CHUNK / enc_gbps / 1e9
    hbm_gbps = (BATCH * (K + M) * CHUNK) / enc_s / 1e9

    result["decode_gbps"] = round(dec_gbps, 2)
    result["hbm_gbps"] = round(hbm_gbps, 1)
    result["hbm_roofline_frac"] = round(hbm_gbps / V5E_HBM_GBPS, 3)
    return enc_gbps


def _measure_baseline_configs(result: dict) -> None:
    """BASELINE configs 1-3: per-plugin encode throughput with the
    config's exact geometry, same loop methodology (fewer reps — these
    are secondary numbers)."""
    import jax.numpy as jnp

    from ceph_tpu.gf import (
        cauchy_good_matrix,
        gf_matrix_to_bitmatrix,
        isa_rs_matrix,
        vandermonde_rs_matrix,
    )

    rng = np.random.default_rng(7)
    configs = [
        # (result key, generator matrix, k, m, chunk bytes, stripes)
        ("jerasure_k4m2_4k_gbps", vandermonde_rs_matrix(4, 2), 4, 2,
         4096, 4096),
        ("isa_k8m3_64k_gbps", isa_rs_matrix(8, 3), 8, 3, 8192, 1024),
        ("cauchy_k10m4_1m_gbps", cauchy_good_matrix(10, 4), 10, 4,
         102400, 1024),
        # the ISA-L documented envelope max (isa/README:23-24)
        ("isa_k21m4_gbps", isa_rs_matrix(21, 4), 21, 4, 65536, 256),
    ]
    for key, gmat, k, m, chunk, stripes in configs:
        try:
            bmat = gf_matrix_to_bitmatrix(np.asarray(gmat)[k:, :])
            data = jnp.asarray(
                rng.integers(0, 256, (stripes, k, chunk), np.uint8)
            )
            gbps = _device_loop_gbps(
                _kernel_apply(bmat), data, n1=5, n2=45, reps=3
            )
            result[key] = round(gbps, 2)
        except Exception:
            pass  # scorecard entries are best-effort; headline must print


def _measure_code_families(result: dict) -> None:
    """Family-level device throughput for every remaining plugin class
    (VERDICT r3 weak #3: the liberation family had no device perf
    numbers at all). Measured through the REAL codec dispatch path —
    registry factory, packetization, engine routing — not a bare
    matmul, so these numbers include what a user actually gets from
    ``encode_chunks``."""
    import jax.numpy as jnp

    from ceph_tpu.codecs import registry

    rng = np.random.default_rng(11)
    families = [
        # (result key, plugin, profile, chunk bytes, stripes)
        ("liberation_k4m2_gbps", "jerasure",
         {"technique": "liberation", "k": "4", "m": "2", "w": "7"},
         7 * 32768, 32),
        ("blaum_roth_k4m2_gbps", "jerasure",
         {"technique": "blaum_roth", "k": "4", "m": "2", "w": "6"},
         6 * 32768, 32),
        ("liber8tion_k4m2_gbps", "jerasure",
         {"technique": "liber8tion", "k": "4", "m": "2", "w": "8"},
         8 * 32768, 32),
        ("lrc_k4m2l3_gbps", "lrc",
         {"k": "4", "m": "2", "l": "3"}, 65536, 128),
        ("shec_k4m3c2_gbps", "shec",
         {"k": "4", "m": "3", "c": "2"}, 65536, 128),
    ]
    for key, plugin, profile, chunk, stripes in families:
        try:
            codec = registry.factory(plugin, dict(profile))
            k = codec.k

            def apply(d, codec=codec, k=k):
                parity = codec.encode_chunks(
                    {i: d[:, i, :] for i in range(k)}
                )
                return jnp.stack(
                    [parity[j] for j in sorted(parity)], axis=1
                )

            data = jnp.asarray(
                rng.integers(0, 256, (stripes, k, chunk), np.uint8)
            )
            gbps = _device_loop_gbps(apply, data, n1=5, n2=25, reps=2)
            result[key] = round(gbps, 2)
        except Exception:
            pass  # scorecard entries are best-effort; headline must print


def _measure_clay_repair(result: dict) -> None:
    """BASELINE config 4: CLAY (8,4,d=11) single-chunk repair, helper
    bytes read per second of host wall time (the repair-bandwidth
    story: (d*chunk)/(d-k+1) instead of k*chunk).

    The repair body is trace-generic (round 3): with jax-array
    helpers the whole plane schedule compiles to ONE device program,
    so the standard on-device loop + trip-count differencing applies
    (a slice of one helper is perturbed per iteration; the output
    folds through a sum so XLA cannot dead-code the repair)."""
    try:
        import jax
        import jax.numpy as jnp

        from ceph_tpu.codecs.registry import registry

        codec = registry.factory(
            "clay", {"k": "8", "m": "4", "d": "11"}
        )
        k, m = 8, 4
        n = k + m
        sub = codec.get_sub_chunk_count()
        chunk = codec.get_chunk_size(k << 16)  # 64 KiB chunks
        sc = chunk // sub
        stripes = 64
        rng = np.random.default_rng(3)
        data = {
            i: rng.integers(0, 256, (stripes, chunk), np.uint8)
            for i in range(k)
        }
        chunks = {
            **data,
            **{
                i: np.asarray(v)
                for i, v in codec.encode_chunks(data).items()
            },
        }
        lost = k + 1  # a parity chunk: full helper-plane read path

        plan = codec.minimum_to_decode({lost}, set(range(n)) - {lost})
        helper, read = {}, 0
        for node, ranges in plan.items():
            parts = [
                chunks[node][..., idx * sc : (idx + cnt) * sc]
                for idx, cnt in ranges
            ]
            read += sum(int(np.prod(p.shape)) for p in parts)
            helper[node] = jnp.asarray(
                np.concatenate(parts, axis=-1)
            )
        keys = sorted(helper)

        @jax.jit
        def loop(arrs, iters):
            def body(i, carry):
                arrs, acc = carry
                first = arrs[0]
                patch = (
                    jax.lax.dynamic_slice(first, (0, 0), (1, 128))
                    ^ jnp.uint8(i + 1)
                )
                arrs = (
                    jax.lax.dynamic_update_slice(
                        first, patch, (0, 0)
                    ),
                ) + arrs[1:]
                out = codec.repair(
                    {lost}, dict(zip(keys, arrs))
                )[lost]
                return arrs, acc + jnp.sum(out, dtype=jnp.uint32)

            _, acc = jax.lax.fori_loop(
                0, iters, body,
                (arrs, jnp.uint32(0)),
            )
            return acc

        arrs = tuple(helper[kk] for kk in keys)
        for trips in (5, 45):
            _timed(loop, arrs, trips)
        dt = _per_iter(loop, arrs, n1=5, n2=45, reps=3)
        result["clay_repair_gbps"] = round(read / dt / 1e9, 2)
        # The hardware-independent MSR story: helper bytes read as a
        # fraction of the k*chunk a naive decode would read.
        result["clay_repair_read_frac"] = round(
            read / (k * chunk * stripes), 3
        )
    except Exception:
        pass


def _measure_smallop_dispatch(result: dict) -> None:
    """Small-op (64 KiB = 8 x 8 KiB) encode throughput: the per-op
    device path (one dispatch + readback per op — what a naive
    pipeline pays per small write) vs the native-ring streaming
    dispatcher aggregating 16 concurrent writers into batched
    dispatches (pipeline/dispatcher.py). Reports aggregate GB/s for
    both, the speedup, and client-observed p99 latency on the
    streamed path."""
    try:
        import threading

        import jax.numpy as jnp

        from ceph_tpu import native
        from ceph_tpu.codecs.registry import registry
        from ceph_tpu.pipeline.dispatcher import StreamingDispatcher

        if not native.available():
            return
        codec = registry.factory("isa", {"k": str(K), "m": str(M)})
        k, chunk = K, 8192
        rng = np.random.default_rng(5)

        # per-op path: sequential device dispatches (jax input forces
        # the device route; readback per op, as a store write needs)
        ops = [
            jnp.asarray(rng.integers(0, 256, (k, chunk), np.uint8))
            for _ in range(16)
        ]
        for o in ops[:2]:  # warm/compile
            p = codec.encode_chunks({i: o[i] for i in range(k)})
            np.asarray(p[k])
        t0 = time.perf_counter()
        for o in ops:
            p = codec.encode_chunks({i: o[i] for i in range(k)})
            np.asarray(p[k])
        perop_s = (time.perf_counter() - t0) / len(ops)
        perop_gbps = k * chunk / perop_s / 1e9

        # streaming path: 16 writers x 24 ops each
        disp = StreamingDispatcher(codec, window_s=0.002)
        try:
            datas = rng.integers(
                0, 256, (16, k, chunk), np.uint8
            )
            lat: list[float] = []
            lat_lock = threading.Lock()

            def worker(i):
                for _ in range(24):
                    t1 = time.perf_counter()
                    disp.encode_sync(datas[i])
                    dt = time.perf_counter() - t1
                    with lat_lock:
                        lat.append(dt)

            # warm (compile the batched shape) before the clock
            disp.encode_sync(datas[0])
            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(16)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        finally:
            disp.stop()
        total_bytes = 16 * 24 * k * chunk
        stream_gbps = total_bytes / wall / 1e9
        result["smallop_perop_gbps"] = round(perop_gbps, 4)
        result["smallop_stream_gbps"] = round(stream_gbps, 4)
        result["smallop_speedup"] = round(stream_gbps / perop_gbps, 1)
        result["smallop_p99_ms"] = round(
            float(np.percentile(np.array(lat) * 1e3, 99)), 2
        )
    except Exception:
        pass


def _measure_single_core(result: dict, enc_gbps: float) -> None:
    """Native C single-core GF encode — the ISA-L-role CPU baseline
    (BASELINE.md target: >= 10x). Same k/m, 1 MiB chunks."""
    try:
        from ceph_tpu import native
        from ceph_tpu.gf import vandermonde_rs_matrix

        if not native.available():
            return
        g = vandermonde_rs_matrix(K, M)
        coding = np.ascontiguousarray(g[K:, :])
        rng = np.random.default_rng(1)
        data = rng.integers(0, 256, (K, CHUNK), np.uint8)
        native.gf_matrix_encode(coding, data)  # warm
        iters, t0 = 8, time.perf_counter()
        for _ in range(iters):
            native.gf_matrix_encode(coding, data)
        dt = (time.perf_counter() - t0) / iters
        cpu_gbps = K * CHUNK / dt / 1e9
        result["single_core_gbps"] = round(cpu_gbps, 3)
        result["vs_single_core"] = round(enc_gbps / cpu_gbps, 1)
    except Exception:
        pass  # baseline is best-effort; the headline must still print


def _measure_reconstruct_latency(result: dict) -> None:
    """p50/p99 single-chunk reconstruct on the host small-op path —
    the low-latency lane beside the bulk device path (SURVEY.md §7
    "small-chunk latency vs batch throughput"). True per-op wall
    time: numpy in, numpy out, no device round trip."""
    from ceph_tpu.codecs.registry import registry

    codec = registry.factory("isa", {"k": str(K), "m": str(M)})
    rng = np.random.default_rng(2)
    data = {i: rng.integers(0, 256, (LAT_CHUNK,), np.uint8) for i in range(K)}
    parity = codec.encode_chunks(data)
    chunks = {**data, **parity}
    del chunks[5]  # one lost data shard, the common repair case
    lat = []
    for _ in range(200):
        t0 = time.perf_counter()
        out = codec.decode_chunks({5}, chunks)
        np.asarray(out[5])
        lat.append(time.perf_counter() - t0)
    lat_ms = np.array(lat) * 1e3
    result["reconstruct_p50_ms"] = round(float(np.percentile(lat_ms, 50)), 3)
    result["reconstruct_p99_ms"] = round(float(np.percentile(lat_ms, 99)), 3)


def _hash_loop_gbps(hash_fn, blocks, n1=N1, n2=N2, reps=3):
    """Device-loop GB/s for a per-block hash kernel over [B, block].
    Same slice-perturb discipline as _device_loop_gbps: bookkeeping
    negligible, no fragile subtraction. Unlike the pallas EC kernel
    (opaque to XLA), parts of the hash path are plain XLA ops — a
    sliced consumer would let XLA dead-code most blocks — so the
    accumulator folds an xor-sum over ALL per-block hashes (a 64 KiB
    read, negligible next to the blocks themselves)."""
    import jax
    import jax.numpy as jnp

    nblocks, block = blocks.shape

    @jax.jit
    def loop(b0, iters):
        def body(i, carry):
            b, acc = carry
            patch = (
                jax.lax.dynamic_slice(b, (0, 0), (1, 128))
                ^ jnp.uint8(i + 1)
            )
            b = jax.lax.dynamic_update_slice(b, patch, (0, 0))
            h = hash_fn(b)
            return b, acc + jnp.sum(h, dtype=jnp.uint32)

        _, acc = jax.lax.fori_loop(
            0, iters, body, (b0, jnp.uint32(0))
        )
        return acc

    for trips in (n1, n2):
        _timed(loop, blocks, trips)
    dt = _per_iter(loop, blocks, n1=n1, n2=n2, reps=reps)
    return nblocks * block / dt / 1e9


def _measure_checksums(result: dict) -> None:
    """BASELINE config 5 (CRC32C over 4/16/64 KiB) + xxhash32/64."""
    try:
        import jax.numpy as jnp

        from ceph_tpu.checksum.crc32c import crc32c_device
    except Exception:
        return
    rng = np.random.default_rng(3)
    size = 64 << 20
    for key, block in (
        ("crc32c_gbps", 4096),
        ("crc32c_16k_gbps", 16384),
        ("crc32c_64k_gbps", 65536),
    ):
        try:
            blocks = jnp.asarray(
                rng.integers(0, 256, (size // block, block), np.uint8)
            )
            reps = 5 if key == "crc32c_gbps" else 3
            gbps = _hash_loop_gbps(
                lambda b: crc32c_device(b, 0xFFFFFFFF), blocks, reps=reps
            )
            result[key] = round(gbps, 1)
        except Exception:
            pass
    try:
        from ceph_tpu.checksum.xxhash import xxh32_device, xxh64_device

        blocks = jnp.asarray(
            rng.integers(0, 256, (size // 4096, 4096), np.uint8)
        )
        result["xxhash32_gbps"] = round(
            _hash_loop_gbps(lambda b: xxh32_device(b), blocks), 1
        )

        def xx64(b):
            import jax.numpy as jnp

            h = xxh64_device(b)
            return (h[0] ^ h[1]).astype(jnp.uint32) if isinstance(
                h, tuple
            ) else h.astype(jnp.uint32)

        result["xxhash64_gbps"] = round(
            _hash_loop_gbps(xx64, blocks), 1
        )
    except Exception:
        pass


def _measure_tunnel_rtt(result: dict) -> None:
    """Record the device round-trip latency alongside the numbers:
    the remote tunnel degrades by 100x+ for hours at a time (observed
    ~0.5 ms vs ~110 ms), and latency-class entries (smallop p99,
    per-op paths) are only meaningful against a healthy RTT. The
    throughput entries cancel RTT by design (trip-count
    differencing), so they stay comparable either way."""
    try:
        import jax
        import jax.numpy as jnp

        x = jnp.asarray(np.zeros((8, 8192), np.uint8))
        # 1-byte readback: a full-array fetch would fold transfer
        # bandwidth into the number and misread a healthy tunnel
        f = jax.jit(lambda a: (a ^ 1)[0, :1])
        np.asarray(f(x))  # warm
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(f(x))
            samples.append(time.perf_counter() - t0)
        result["tunnel_rtt_ms"] = round(min(samples) * 1e3, 2)
    except Exception:
        pass


def main() -> None:
    result: dict = {}
    _measure_tunnel_rtt(result)
    enc_gbps = _measure_device_path(result)
    _measure_baseline_configs(result)
    _measure_code_families(result)
    _measure_clay_repair(result)
    _measure_smallop_dispatch(result)
    _measure_single_core(result, enc_gbps)
    _measure_reconstruct_latency(result)
    _measure_checksums(result)
    print(
        json.dumps(
            {
                "metric": f"EC({K},{M}) reed_sol_van batched stripe encode",
                "value": round(enc_gbps, 2),
                "unit": "GB/s data-in per chip",
                "vs_baseline": round(enc_gbps / TARGET_GBPS, 3),
                **result,
            }
        )
    )


if __name__ == "__main__":
    main()
