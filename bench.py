"""Flagship benchmark: EC(8,4) Reed-Solomon batched stripe encode.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline target: 25 GB/s/chip on TPU v5e-1 (BASELINE.json north star).
``vs_baseline`` is the ratio value / 25.

Methodology mirrors the reference tool's shape
(src/test/erasure-code/ceph_erasure_code_benchmark.cc: big buffer,
fixed iteration count, throughput = bytes/elapsed) with one TPU-ism:
iterations are enqueued without per-call sync (per-dispatch sync
latency through the device tunnel would measure the network, not the
chip) and the clock stops on the final block_until_ready.
"""

from __future__ import annotations

import json
import time

import numpy as np

K, M = 8, 4
CHUNK = 1 << 20          # 1 MiB per shard
BATCH = 8                # stripes per dispatch -> 64 MiB input per iter
ITERS = 30
TARGET_GBPS = 25.0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ceph_tpu.gf import gf_matrix_to_bitmatrix, vandermonde_rs_matrix
    from ceph_tpu.ops.bitplane import gf_encode_bitplane

    g = vandermonde_rs_matrix(K, M)
    bmat = jnp.asarray(gf_matrix_to_bitmatrix(g[K:, :]))
    rng = np.random.default_rng(0)
    data = jnp.asarray(
        rng.integers(0, 256, (BATCH, K, CHUNK)).astype(np.uint8)
    )
    enc = jax.jit(gf_encode_bitplane)
    enc(bmat, data).block_until_ready()  # compile + warm

    t0 = time.perf_counter()
    out = None
    for _ in range(ITERS):
        out = enc(bmat, data)
    out.block_until_ready()
    elapsed = time.perf_counter() - t0

    total_bytes = ITERS * BATCH * K * CHUNK
    gbps = total_bytes / elapsed / 1e9
    print(
        json.dumps(
            {
                "metric": f"EC({K},{M}) reed_sol_van batched stripe encode",
                "value": round(gbps, 2),
                "unit": "GB/s data-in per chip",
                "vs_baseline": round(gbps / TARGET_GBPS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
