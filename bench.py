"""Flagship benchmark: EC(8,4) Reed-Solomon batched stripe encode.

Prints ONE JSON line. Headline fields {"metric", "value", "unit",
"vs_baseline"} report the encode throughput against the 25 GB/s/chip
target (BASELINE.json north star); extra fields cover the rest of the
BASELINE.md scorecard:

  decode_gbps        on-chip reconstruct of 4 lost data shards from 8
                     survivors (same bytes-in basis as encode)
  vs_single_core     encode speedup over the native C single-core GF
                     path (the ISA-L-role baseline, BASELINE.md target
                     ">= 10x"); absent if the native lib is unavailable
  hbm_gbps /         achieved HBM traffic (data-in + parity-out per
  hbm_roofline_frac  encode) vs the ~819 GB/s v5e roofline
  reconstruct_p50_ms / p99  single-chunk (64 KiB) reconstruct latency on
                     the host small-op path (true per-op wall time — the
                     low-latency path beside the bulk device path)
  crc32c_gbps        deep-scrub checksum kernel over 4 KiB blocks
                     (BASELINE config 5), same on-device loop +
                     differencing methodology

Methodology — honest under the axon device tunnel, where
``block_until_ready`` resolves without waiting for remote execution
and any real sync costs a ~0.1-0.5 s round trip:

1. The iteration loop runs ON DEVICE (``lax.fori_loop``); each
   iteration perturbs the input (so the encode is not loop-invariant)
   and XOR-folds the parity into an accumulator the final readback
   depends on — execution cannot be elided or overlapped away.
2. Work is forced by reading back one byte of the accumulator
   (``np.asarray``), not by ``block_until_ready``.
3. The fixed tunnel round trip is cancelled by differencing two trip
   counts: per_iter = (t(N2) - t(N1)) / (N2 - N1).
4. A perturb-only loop measured the same way is subtracted so the
   reported number is the encode alone.
5. Differenced estimates are noisy under tunnel-latency jitter — a
   hiccup on the short trip makes a diff NEGATIVE. Each estimate is
   the median of the positive diffs over several repeats (r1 took the
   min, which once picked a glitch and printed 6.7e7 GB/s).

The reference tool's spirit is kept (big buffer, fixed iteration
count, throughput = bytes/elapsed —
src/test/erasure-code/ceph_erasure_code_benchmark.cc) with the timing
adapted to remote-device reality.
"""

from __future__ import annotations

import json
import time

import numpy as np

K, M = 8, 4
CHUNK = 1 << 20          # 1 MiB per shard
BATCH = 8                # stripes per dispatch -> 64 MiB input per iter
N1, N2 = 10, 110  # large span: the diff must dwarf tunnel RTT jitter
REPS = 5
TARGET_GBPS = 25.0
V5E_HBM_GBPS = 819.0     # v5e-1 HBM bandwidth (public spec)
LAT_CHUNK = 1 << 16      # 64 KiB single-chunk reconstruct latency probe


def _timed(fn, *args) -> float:
    t0 = time.perf_counter()
    np.asarray(fn(*args))  # readback forces real remote execution
    return time.perf_counter() - t0


def _per_iter(fn, *args) -> float:
    """Median of positive differenced estimates (see module docstring)."""
    diffs = []
    for _ in range(REPS):
        d = (_timed(fn, *args, N2) - _timed(fn, *args, N1)) / (N2 - N1)
        if d > 0:
            diffs.append(d)
    if not diffs:
        raise RuntimeError("all differenced timings were negative")
    return float(np.median(diffs))


def _loop_apply(encode, out_shards):
    """On-device timing loop: perturb + apply + XOR-fold accumulator."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def loop(data, iters):
        def body(i, carry):
            d, acc = carry
            d = jnp.bitwise_xor(d, jnp.uint8(i + 1))
            return d, jnp.bitwise_xor(acc, encode(d))

        _, acc = jax.lax.fori_loop(
            0, iters, body,
            (data, jnp.zeros((BATCH, out_shards, CHUNK), jnp.uint8)),
        )
        return acc[0, 0, 0]

    return loop


def _measure_device_path(result: dict) -> float:
    import jax
    import jax.numpy as jnp

    from ceph_tpu.gf import (
        decode_matrix,
        gf_matrix_to_bitmatrix,
        vandermonde_rs_matrix,
    )
    from ceph_tpu.ops import pallas_encode as pe
    from ceph_tpu.ops.bitplane import gf_encode_bitplane

    g = vandermonde_rs_matrix(K, M)
    enc_bmat_np = gf_matrix_to_bitmatrix(g[K:, :])

    # Decode config: lose data shards 4-7, survive on 0-3 + all parity
    # (the exhaustive-erasures tool's worst standard case: a full-m
    # erasure needing true matrix reconstruct, not passthrough).
    present = [0, 1, 2, 3, 8, 9, 10, 11]
    want = [4, 5, 6, 7]
    dmat = decode_matrix(g, K, present)  # [k, len(present)]
    dec_rows = np.stack([dmat[w, :] for w in want])
    dec_bmat_np = gf_matrix_to_bitmatrix(dec_rows)

    rng = np.random.default_rng(0)
    data = jnp.asarray(
        rng.integers(0, 256, (BATCH, K, CHUNK)).astype(np.uint8)
    )

    on_tpu = pe.on_tpu()

    def make_apply(bmat_np):
        if on_tpu:
            big = jnp.asarray(pe._folded_bitmatrix(bmat_np, pe.FOLD))

            def apply(d):
                return pe._encode_tiled(big, d, pe.FOLD, interpret=False)

            return apply
        dev = jnp.asarray(bmat_np)
        return lambda d: gf_encode_bitplane(dev, d)

    loop_enc = _loop_apply(make_apply(enc_bmat_np), M)
    loop_dec = _loop_apply(make_apply(dec_bmat_np), M)

    @jax.jit
    def loop_perturb(data, iters):
        def body(i, carry):
            d, acc = carry
            d = jnp.bitwise_xor(d, jnp.uint8(i + 1))
            return d, jnp.bitwise_xor(acc, d[:, :M, :])

        _, acc = jax.lax.fori_loop(
            0, iters, body,
            (data, jnp.zeros((BATCH, M, CHUNK), jnp.uint8)),
        )
        return acc[0, 0, 0]

    # compile + warm every loop at both trip counts
    for loop in (loop_enc, loop_dec, loop_perturb):
        for n in (N1, N2):
            _timed(loop, data, n)

    pert_s = _per_iter(loop_perturb, data)
    enc_s = max(_per_iter(loop_enc, data) - pert_s, 1e-9)
    dec_s = max(_per_iter(loop_dec, data) - pert_s, 1e-9)

    bytes_in = BATCH * K * CHUNK
    enc_gbps = bytes_in / enc_s / 1e9
    dec_gbps = bytes_in / dec_s / 1e9
    hbm_gbps = (BATCH * (K + M) * CHUNK) / enc_s / 1e9

    result["decode_gbps"] = round(dec_gbps, 2)
    result["hbm_gbps"] = round(hbm_gbps, 1)
    result["hbm_roofline_frac"] = round(hbm_gbps / V5E_HBM_GBPS, 3)
    return enc_gbps


def _measure_single_core(result: dict, enc_gbps: float) -> None:
    """Native C single-core GF encode — the ISA-L-role CPU baseline
    (BASELINE.md target: >= 10x). Same k/m, 1 MiB chunks."""
    try:
        from ceph_tpu import native
        from ceph_tpu.gf import vandermonde_rs_matrix

        if not native.available():
            return
        g = vandermonde_rs_matrix(K, M)
        coding = np.ascontiguousarray(g[K:, :])
        rng = np.random.default_rng(1)
        data = rng.integers(0, 256, (K, CHUNK), np.uint8)
        native.gf_matrix_encode(coding, data)  # warm
        iters, t0 = 8, time.perf_counter()
        for _ in range(iters):
            native.gf_matrix_encode(coding, data)
        dt = (time.perf_counter() - t0) / iters
        cpu_gbps = K * CHUNK / dt / 1e9
        result["single_core_gbps"] = round(cpu_gbps, 3)
        result["vs_single_core"] = round(enc_gbps / cpu_gbps, 1)
    except Exception:
        pass  # baseline is best-effort; the headline must still print


def _measure_reconstruct_latency(result: dict) -> None:
    """p50/p99 single-chunk reconstruct on the host small-op path —
    the low-latency lane beside the bulk device path (SURVEY.md §7
    "small-chunk latency vs batch throughput"). True per-op wall
    time: numpy in, numpy out, no device round trip."""
    from ceph_tpu.codecs.registry import registry

    codec = registry.factory("isa", {"k": str(K), "m": str(M)})
    rng = np.random.default_rng(2)
    data = {i: rng.integers(0, 256, (LAT_CHUNK,), np.uint8) for i in range(K)}
    parity = codec.encode_chunks(data)
    chunks = {**data, **parity}
    del chunks[5]  # one lost data shard, the common repair case
    lat = []
    for _ in range(200):
        t0 = time.perf_counter()
        out = codec.decode_chunks({5}, chunks)
        np.asarray(out[5])
        lat.append(time.perf_counter() - t0)
    lat_ms = np.array(lat) * 1e3
    result["reconstruct_p50_ms"] = round(float(np.percentile(lat_ms, 50)), 3)
    result["reconstruct_p99_ms"] = round(float(np.percentile(lat_ms, 99)), 3)


def _measure_crc(result: dict) -> None:
    """CRC32C over 4 KiB blocks (BASELINE config 5) on the device
    fold kernel, timed with the same loop + differencing."""
    try:
        import jax
        import jax.numpy as jnp

        from ceph_tpu.checksum.crc32c import crc32c_device

        size, block = 64 << 20, 4096
        rng = np.random.default_rng(3)
        blocks = jnp.asarray(
            rng.integers(0, 256, (size // block, block), np.uint8)
        )
    except Exception:
        return  # the headline must still print

    @jax.jit
    def loop(b, iters):
        def body(i, carry):
            b, acc = carry
            b = jnp.bitwise_xor(b, jnp.uint8(i + 1))
            return b, jnp.bitwise_xor(acc, crc32c_device(b, 0xFFFFFFFF))

        _, acc = jax.lax.fori_loop(
            0, iters, body,
            (b, jnp.zeros((size // block,), jnp.uint32)),
        )
        return acc[0]

    @jax.jit
    def pert(b, iters):
        def body(i, carry):
            b, acc = carry
            b = jnp.bitwise_xor(b, jnp.uint8(i + 1))
            return b, jnp.bitwise_xor(acc, b[:, 0].astype(jnp.uint32))

        _, acc = jax.lax.fori_loop(
            0, iters, body,
            (b, jnp.zeros((size // block,), jnp.uint32)),
        )
        return acc[0]

    try:
        for n in (N1, N2):
            _timed(loop, blocks, n)
            _timed(pert, blocks, n)
        dt = max(
            _per_iter(loop, blocks) - _per_iter(pert, blocks), 1e-9
        )
        result["crc32c_gbps"] = round(size / dt / 1e9, 1)
    except Exception:
        pass  # the headline must still print


def main() -> None:
    result: dict = {}
    enc_gbps = _measure_device_path(result)
    _measure_single_core(result, enc_gbps)
    _measure_reconstruct_latency(result)
    _measure_crc(result)
    print(
        json.dumps(
            {
                "metric": f"EC({K},{M}) reed_sol_van batched stripe encode",
                "value": round(enc_gbps, 2),
                "unit": "GB/s data-in per chip",
                "vs_baseline": round(enc_gbps / TARGET_GBPS, 3),
                **result,
            }
        )
    )


if __name__ == "__main__":
    main()
